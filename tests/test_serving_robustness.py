"""Serving SLO guardrail tests (inference/serving.py robustness layer).

Reference analog: the predictor error-handling / service-recovery seam
around the inference runtime; the per-request-isolation requirement is
the Orca/vLLM correctness bar (requests sharing a batch must not be
able to corrupt each other).

The load-bearing guarantees under test:
- every submitted request resolves EXACTLY ONCE with a terminal
  finish_reason from TERMINAL_REASONS — backpressure, TTL, deadlines,
  cancellation, quarantine, eviction and max_ticks all funnel through
  the same `_finish` transition;
- the in-jit poisoned-slot quarantine evicts ONLY the poisoned slot
  and co-batched streams stay bit-identical to their solo greedy runs;
- a raising/stalling device call self-heals (slot rollback, mirror
  resync, bounded retry) without perturbing surviving streams, and the
  guardrails cost zero recompiles (trace-count ceilings unchanged).
"""
import os
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.inference.serving import (ServingEngine, BackpressureError,
                                          TERMINAL_REASONS)
from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                   greedy_generate)
from paddle_tpu.profiler import monitor
from paddle_tpu.testing import faults

MAXLEN = 32


def _gpt_cfg():
    return GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=2, ffn_hidden=64, max_seq_len=64,
                     sequence_parallel=False, remat=False,
                     dtype=jnp.float32)


@pytest.fixture(scope="module")
def gpt_setup():
    cfg = _gpt_cfg()
    return cfg, init_gpt_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture
def clean_faults():
    yield
    faults.uninstall()


@pytest.fixture(autouse=True)
def _clean_flight_ring():
    """The engine notes serving faults into the PROCESS-GLOBAL flight
    recorder ring; leaving them behind would leak into other tests'
    dumps (e.g. the resilient trainer's rollback dump asserts over its
    step records). Clear the ring after every test here."""
    from paddle_tpu.profiler import flight_recorder
    yield
    rec = flight_recorder.recorder()
    rec.clear()
    rec.set_dir(None)


def _prompts(lens, seed=0, vocab=64):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


def _want(params, cfg, prompt, n):
    out = greedy_generate(params, jnp.asarray(prompt)[None], cfg, n,
                          max_len=MAXLEN)
    return np.asarray(out)[0, len(prompt):]


def _engine(params, cfg, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", MAXLEN)
    return ServingEngine(params, cfg, family="gpt", **kw)


def _assert_resolved(reqs):
    for r in reqs:
        assert r.done and r.finish_reason in TERMINAL_REASONS, \
            (r.id, r.done, r.finish_reason)
        assert r.slot is None


def _assert_clean(eng):
    """Engine invariant after faults: no slot leaked, mirrors agree."""
    assert all(r is None for r in eng._slot_req)
    assert not eng._active.any()
    assert not eng._queue


# --------------------------------------------------------------------------
# admission control: backpressure, TTL, cancellation
# --------------------------------------------------------------------------
class TestAdmissionControl:
    def test_backpressure_reject(self, gpt_setup):
        cfg, params = gpt_setup
        rej0 = monitor.counter("serving.rejected").value
        eng = _engine(params, cfg, num_slots=1, max_queue=2)
        prompts = _prompts([3, 4, 5, 6], seed=1)
        ok = [eng.submit(prompts[0], 3), eng.submit(prompts[1], 3)]
        with pytest.raises(BackpressureError) as ei:
            eng.submit(prompts[2], 3)
        assert ei.value.queue_depth == 2
        assert monitor.counter("serving.rejected").value == rej0 + 1
        eng.drain()
        _assert_resolved(ok)
        for p, r in zip(prompts, ok):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _want(params, cfg, p, 3))

    def test_shed_oldest_policy(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1, max_queue=1,
                      queue_policy="shed_oldest")
        prompts = _prompts([3, 4, 5], seed=2)
        reqs = [eng.submit(p, 3) for p in prompts]   # never raises
        # r1 was shed from the queue to make room for r2
        assert reqs[1].done and reqs[1].finish_reason == "evicted"
        assert reqs[1].tokens == []
        eng.drain()
        _assert_resolved(reqs)
        np.testing.assert_array_equal(
            np.asarray(reqs[2].tokens, np.int32),
            _want(params, cfg, prompts[2], 3))

    def test_queue_ttl_expires_waiting_request(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1, queue_ttl_s=0.05)
        long_p, short_p = _prompts([4, 3], seed=3)
        r1 = eng.submit(long_p, 8)
        r2 = eng.submit(short_p, 8)
        eng.step()               # r1 admitted; r2 still queued
        assert r1.slot is not None and not r2.done
        time.sleep(0.1)          # r2's wait exceeds the TTL
        eng.drain()
        assert r2.finish_reason == "timeout" and r2.tokens == []
        assert r1.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(r1.tokens, np.int32),
            _want(params, cfg, long_p, 8))

    def test_cancel_queued_and_mid_decode(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1)
        pa, pb = _prompts([5, 7], seed=4)
        ra = eng.submit(pa, 8)
        rb = eng.submit(pb, 6)
        eng.step()
        eng.step()
        assert ra.slot is not None
        assert ra.cancel() is True          # mid-decode: frees the slot
        assert ra.finish_reason == "cancelled" and ra.done
        assert ra.cancel() is False         # exactly-once
        assert ra.finish_reason == "cancelled"
        # the freed slot admits rb, whose stream is still exact
        eng.drain()
        assert rb.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(rb.tokens, np.int32), _want(params, cfg, pb, 6))
        # the cancelled stream is an exact prefix of its solo run
        want_a = _want(params, cfg, pa, 8)
        np.testing.assert_array_equal(
            np.asarray(ra.tokens, np.int32), want_a[:len(ra.tokens)])

    def test_cancel_queued_removes_from_queue(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1)
        pa, pb = _prompts([4, 5], seed=5)
        eng.submit(pa, 4)
        rb = eng.submit(pb, 4)
        assert rb.cancel() is True          # still queued
        assert rb.finish_reason == "cancelled"
        eng.drain()
        _assert_clean(eng)


# --------------------------------------------------------------------------
# deadlines
# --------------------------------------------------------------------------
class TestDeadlines:
    def test_deadline_ticks(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=2)
        pa, pb = _prompts([4, 6], seed=6)
        ra = eng.submit(pa, 20)
        rb = eng.submit(pb, 20, deadline_ticks=3)
        eng.drain()
        assert rb.finish_reason == "timeout"
        assert 0 < len(rb.tokens) < 20
        # survivor unperturbed
        assert ra.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(ra.tokens, np.int32), _want(params, cfg, pa, 20))
        # timed-out stream is an exact prefix
        np.testing.assert_array_equal(
            np.asarray(rb.tokens, np.int32),
            _want(params, cfg, pb, 20)[:len(rb.tokens)])

    def test_deadline_s_dead_on_arrival(self, gpt_setup):
        cfg, params = gpt_setup
        t0 = monitor.counter("serving.timeout").value
        eng = _engine(params, cfg)
        r = eng.submit(_prompts([4], seed=7)[0], 4, deadline_s=0.0)
        eng.drain()
        assert r.finish_reason == "timeout" and r.tokens == []
        assert monitor.counter("serving.timeout").value == t0 + 1
        assert not eng.has_work()

    def test_generate_deadline_passthrough(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg)
        outs = eng.generate(_prompts([3, 5], seed=8), 12,
                            deadline_ticks=4)
        for o in outs:
            # prefill token + decode tokens until the tick clock passes
            # the deadline (enforced after the tick's emissions)
            assert 0 < len(o) == 6 < 12


# --------------------------------------------------------------------------
# poisoned-slot quarantine
# --------------------------------------------------------------------------
class TestQuarantine:
    def test_nan_logits_evicts_only_poisoned_slot(self, gpt_setup,
                                                  clean_faults):
        cfg, params = gpt_setup
        p0 = monitor.counter("serving.poisoned").value
        prompts = _prompts([3, 5, 8, 10], seed=9)
        faults.install("nan_logits@2:1")
        eng = _engine(params, cfg, num_slots=2)
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.drain()
        reasons = [r.finish_reason for r in reqs]
        assert reasons.count("poisoned") == 1, reasons
        assert monitor.counter("serving.poisoned").value == p0 + 1
        for p, r in zip(prompts, reqs):
            want = _want(params, cfg, p, 6)
            got = np.asarray(r.tokens, np.int32)
            if r.finish_reason == "poisoned":
                np.testing.assert_array_equal(got, want[:len(got)])
            else:                       # survivors: bit-identical
                assert r.finish_reason == "length"
                np.testing.assert_array_equal(got, want)
        _assert_clean(eng)

    def test_prefill_quarantine_on_nan_params(self, gpt_setup):
        """Organic non-finite logits at PREFILL: the request resolves
        as "poisoned" at admission and never occupies a slot."""
        cfg, params = gpt_setup
        bad = dict(params)
        bad["wte"] = jnp.full_like(params["wte"], jnp.nan)
        eng = _engine(bad, cfg)
        r = eng.submit(_prompts([4], seed=10)[0], 4)
        eng.drain()
        assert r.finish_reason == "poisoned" and r.tokens == []
        _assert_clean(eng)

    def test_zero_recompiles_with_guardrails(self, gpt_setup,
                                             clean_faults):
        """Acceptance: guardrails (quarantine + a fired poison event)
        add zero traces — decode holds one trace per sampling mode."""
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=2)
        eng.generate(_prompts([3, 9, 5, 16], seed=11), 3)
        dec0, pre0 = eng.trace_counts()
        assert dec0 == 1
        faults.install("nan_logits@0:0")
        eng.generate(_prompts([7, 2, 11], seed=12), 5)
        faults.uninstall()
        assert eng.trace_counts() == (dec0, pre0)


# --------------------------------------------------------------------------
# self-healing tick (satellite: exception safety in step()/_admit())
# --------------------------------------------------------------------------
class TestSelfHealing:
    def test_prefill_raise_rolls_back_and_retries(self, gpt_setup,
                                                  clean_faults):
        cfg, params = gpt_setup
        f0 = monitor.counter("serving.faults").value
        prompts = _prompts([3, 5, 8], seed=13)
        faults.install("prefill_raise@0")
        eng = _engine(params, cfg, num_slots=2)
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.drain()
        assert monitor.counter("serving.faults").value > f0
        _assert_resolved(reqs)
        for p, r in zip(prompts, reqs):       # fault fully transparent
            assert r.finish_reason == "length"
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _want(params, cfg, p, 5))
        _assert_clean(eng)

    def test_decode_raise_resyncs_and_retries(self, gpt_setup,
                                              clean_faults):
        cfg, params = gpt_setup
        prompts = _prompts([4, 7], seed=14)
        faults.install("decode_raise@2")
        eng = _engine(params, cfg, num_slots=2)
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.drain()
        for p, r in zip(prompts, reqs):
            assert r.finish_reason == "length"
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _want(params, cfg, p, 6))

    def test_prefill_retries_exhausted_evicts_not_limbo(self, gpt_setup):
        """Regression (satellite): a persistently-raising prefill must
        roll the slot back and resolve the request — the pre-fix code
        lost the popped request and left step() raising."""
        cfg, params = gpt_setup
        eng = _engine(params, cfg, retries=1, backoff_base=0.0)
        boom = {"n": 0}
        real = eng._prefill

        def raising(*a, **k):
            boom["n"] += 1
            raise RuntimeError("injected dispatch failure")
        eng._prefill = raising
        r = eng.submit(_prompts([4], seed=15)[0], 4)
        eng.drain()
        assert boom["n"] == 2                 # initial + 1 retry
        assert r.finish_reason == "evicted" and r.tokens == []
        _assert_clean(eng)
        # the engine still serves: restore and run an exact stream
        eng._prefill = real
        p = _prompts([5], seed=16)[0]
        out = eng.generate([p], 4)[0]
        np.testing.assert_array_equal(out, _want(params, cfg, p, 4))

    def test_decode_retries_exhausted_hard_resets(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, retries=0)
        real = eng._decode

        def raising(*a, **k):
            raise RuntimeError("injected dispatch failure")
        prompts = _prompts([4, 6], seed=17)
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.step()                        # admits both
        eng._decode = raising
        eng.step()                        # decode fails -> hard reset
        _assert_resolved(reqs)
        assert all(r.finish_reason == "evicted" for r in reqs)
        _assert_clean(eng)
        # fresh pool cache serves exact streams afterwards
        eng._decode = real
        out = eng.generate([prompts[0]], 4)[0]
        np.testing.assert_array_equal(out,
                                      _want(params, cfg, prompts[0], 4))

    def test_watchdog_puller_recovers_after_hang(self):
        """A pull that exhausts the budget abandons the wedged worker:
        the next, healthy pull must not queue behind the dead one."""
        from paddle_tpu.parallel.resilience import (WatchdogPuller,
                                                    StepHungError)
        p = WatchdogPuller(label="test")
        with pytest.raises(StepHungError):
            p.pull(lambda: (time.sleep(2.0), np.ones(1))[1],
                   timeout=0.05, retries=1, backoff_base=0.05,
                   backoff_max=0.05)
        t0 = time.perf_counter()
        out = p.pull(lambda: np.full((2,), 7.0), timeout=1.0, retries=1)
        assert time.perf_counter() - t0 < 1.0
        np.testing.assert_array_equal(out, np.full((2,), 7.0))

    def test_tick_stall_recovers_under_watchdog(self, gpt_setup,
                                                clean_faults):
        cfg, params = gpt_setup
        r0 = monitor.counter("serving.retries").value
        faults.install("tick_stall@1:300")
        eng = _engine(params, cfg, watchdog_timeout=0.1, retries=3,
                      backoff_base=0.2)
        p = _prompts([4], seed=18)[0]
        out = eng.generate([p], 5)[0]
        assert monitor.counter("serving.retries").value > r0
        np.testing.assert_array_equal(out, _want(params, cfg, p, 5))


# --------------------------------------------------------------------------
# no-limbo: abort_pending / generate(max_ticks=) (satellite)
# --------------------------------------------------------------------------
class TestNoLimbo:
    def test_abort_pending_resolves_everything(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1)
        prompts = _prompts([4, 5, 6], seed=19)
        reqs = [eng.submit(p, 10) for p in prompts]
        eng.step()
        n = eng.abort_pending()
        assert n == 3
        _assert_resolved(reqs)
        assert all(r.finish_reason == "evicted" for r in reqs)
        assert not eng.has_work()
        with pytest.raises(ValueError):
            eng.abort_pending(reason="nonsense")

    def test_generate_max_ticks_never_limbo(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg, num_slots=1)
        prompts = _prompts([4, 5, 6, 7], seed=20)
        outs = eng.generate(prompts, 10, max_ticks=3)
        assert not eng.has_work()           # nothing left behind
        _assert_clean(eng)
        for p, o in zip(prompts, outs):     # partials are exact prefixes
            want = _want(params, cfg, p, 10)
            np.testing.assert_array_equal(o, want[:len(o)])
        assert any(len(o) < 10 for o in outs)

    def test_drain_without_max_ticks_still_completes(self, gpt_setup):
        cfg, params = gpt_setup
        eng = _engine(params, cfg)
        outs = eng.generate(_prompts([3, 5], seed=21), 4)
        assert all(len(o) == 4 for o in outs)


# --------------------------------------------------------------------------
# observability: counters, queue_wait gauge, SLO percentiles
# --------------------------------------------------------------------------
class TestObservability:
    def test_queue_wait_histogram_and_cancel_counter(self, gpt_setup):
        cfg, params = gpt_setup
        c0 = monitor.counter("serving.cancelled").value
        eng = _engine(params, cfg)
        r = eng.submit(_prompts([4], seed=22)[0], 6)
        eng.step()
        # queue wait moved from a last-write-wins gauge onto a bounded-
        # reservoir histogram (PR 11): percentiles in the snapshot
        h = monitor.histogram("serving.queue_wait_ms").value
        assert h["n"] >= 1 and h["p50"] >= 0.0 and h["p99"] >= h["p50"]
        r.cancel()
        assert monitor.counter("serving.cancelled").value == c0 + 1

    def test_slo_export_and_report(self, gpt_setup, tmp_path):
        cfg, params = gpt_setup
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        from telemetry_report import summarize
        eng = _engine(params, cfg)
        eng.generate(_prompts([4, 6, 9], seed=23), 6)
        snap = eng.slo_snapshot()
        assert len(snap["ttft_ms"]) == 3
        assert len(snap["itl_ms"]) == 15        # 3 requests x 5 ticks
        path = str(tmp_path / "slo.jsonl")
        monitor.registry().export_jsonl(path)
        eng.export_slo_jsonl(path)
        doc = summarize(path)
        srv = doc["serving"]
        for section in ("ttft", "inter_token"):
            assert {"n", "p50_ms", "p95_ms", "p99_ms"} <= set(srv[section])
            assert srv[section]["p50_ms"] <= srv[section]["p99_ms"]
        # export DRAINS the rings: a periodic re-export contributes no
        # duplicate samples, so merged percentile counts are stable
        eng.export_slo_jsonl(path)
        doc2 = summarize(path)
        assert doc2["serving"]["ttft"]["n"] == srv["ttft"]["n"]
        assert doc2["serving"]["inter_token"]["n"] == \
            srv["inter_token"]["n"]

    def test_flight_dump_on_poison(self, gpt_setup, tmp_path,
                                   clean_faults):
        from paddle_tpu.profiler import flight_recorder
        cfg, params = gpt_setup
        rec = flight_recorder.recorder()
        rec.clear()
        rec.set_dir(str(tmp_path))
        try:
            faults.install("nan_logits@1:0")
            eng = _engine(params, cfg)
            eng.generate(_prompts([4], seed=24), 6)
        finally:
            rec.set_dir(None)
            faults.uninstall()
        dumps = [f for f in os.listdir(str(tmp_path))
                 if f.endswith(".json") and "poisoned" in f]
        assert dumps
        doc = flight_recorder.load_dump(
            os.path.join(str(tmp_path), dumps[0]))
        assert doc["kind"] == "flight_recorder"
        assert "monitor" in doc


# --------------------------------------------------------------------------
# facade passthrough
# --------------------------------------------------------------------------
class TestFacadePassthrough:
    def test_engine_kw_and_deadline_passthrough(self, gpt_setup):
        cfg, _ = gpt_setup
        from paddle_tpu.models.gpt import GPTModel
        gm = GPTModel(cfg)
        prompts = _prompts([4, 6], seed=25)
        outs = gm.generate(prompts, 8, num_slots=2, max_len=MAXLEN,
                           deadline_ticks=3, max_ticks=16)
        assert all(0 < len(o) == 5 < 8 for o in outs)
        eng = gm._serving_engine
        # same knobs -> cached engine; new engine knob -> rebuild
        gm.generate(prompts, 4, num_slots=2, max_len=MAXLEN)
        assert gm._serving_engine is eng
        gm.generate(prompts, 4, num_slots=2, max_len=MAXLEN,
                    max_queue=8)
        assert gm._serving_engine is not eng
        assert gm._serving_engine.max_queue == 8


# --------------------------------------------------------------------------
# overload terminality (PR-20 satellite: the trace-leak regression)
# --------------------------------------------------------------------------
class TestOverloadTraceLeak:
    """Every shed / suspended / quota-rejected request must leave
    EXACTLY one terminal trace span and one journal terminal event —
    BEFORE any error propagates to the caller. A leak here means an
    open root span pinned in the tracer forever and a journal admit
    that would spuriously replay after a crash."""

    def _router(self, params, cfg, **kw):
        from paddle_tpu.inference.router import create_router
        kw.setdefault("replicas", 1)
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_len", MAXLEN)
        kw.setdefault("concurrent", False)
        kw.setdefault("tracing", True)
        return create_router(params, cfg, family="gpt", **kw)

    def _assert_no_leaks(self, router):
        """One terminal span per trace; journal admits all terminated."""
        from paddle_tpu.profiler import tracing
        tr = tracing.tracer()
        for tid in tr.trace_ids():
            assert len(tr.terminal_spans(tid)) == 1, tid
        j = router.stats().get("journal")
        if j is not None:
            assert j["replayable"] == 0

    def test_quota_reject_terminal_before_raise(self, gpt_setup,
                                                tmp_path):
        from paddle_tpu.inference.admission import (TenantQuota,
                                                    QuotaExceededError)
        from paddle_tpu.profiler import tracing
        cfg, params = gpt_setup
        tracing.clear()
        router = self._router(
            params, cfg, journal_dir=str(tmp_path),
            admission={"t": TenantQuota(tokens_per_s=1.0, burst=4.0)})
        with pytest.raises(QuotaExceededError) as ei:
            router.submit(_prompts([3], seed=30)[0], 8, tenant="t")
        assert ei.value.retry_after_s > 0
        tr = tracing.tracer()
        assert len(tr.trace_ids()) == 1
        terms = tr.terminal_spans(tr.trace_ids()[0])
        assert len(terms) == 1
        assert terms[0].attrs["reason"] == "rejected"
        j = router.stats()["journal"]
        # end-only record: never admitted, never replayable
        assert j["admits"] == 0 and j["ends"] == 1
        assert j["replayable"] == 0
        router.close()

    def test_shed_terminal_once(self, gpt_setup, tmp_path):
        from paddle_tpu.profiler import tracing
        cfg, params = gpt_setup
        tracing.clear()
        # cap the ENGINE queue so dispatch refuses and requests pool in
        # the router's own pending deque (create_router's engines take
        # an unbounded queue that would swallow everything)
        from paddle_tpu.inference.router import EngineRouter
        eng = _engine(params, cfg, num_slots=2, max_queue=1)
        router = EngineRouter([eng], tracing=True, admission={},
                              journal_dir=str(tmp_path))
        prompts = _prompts([3, 4, 5, 6], seed=31)
        reqs = [router.submit(p, 6) for p in prompts]
        assert router.stats()["pending"] >= 1
        shed = router.shed_oldest_pending(1)
        assert shed == 1
        victim = [r for r in reqs if r.done][0]
        assert victim.finish_reason == "evicted"
        router.drain()
        _assert_resolved(reqs)
        self._assert_no_leaks(router)
        j = router.stats()["journal"]
        assert j["admits"] == len(reqs) and j["ends"] == len(reqs)
        router.close()

    def test_suspend_resume_terminal_once(self, gpt_setup, tmp_path):
        from paddle_tpu.profiler import tracing
        cfg, params = gpt_setup
        tracing.clear()
        router = self._router(params, cfg, journal_dir=str(tmp_path),
                              admission={})
        prompts = _prompts([3, 4, 5], seed=32)
        low = [router.submit(p, 10, priority=0) for p in prompts[:2]]
        for _ in range(3):
            router.step()
        hi = router.submit(prompts[2], 10, priority=5)
        assert router.stats()["suspended"] == 1
        router.drain()
        _assert_resolved(low + [hi])
        from paddle_tpu.profiler import tracing as _t
        tr = _t.tracer()
        victim = [r for r in low if r.requeues == 0 and any(
            s.name == "suspend"
            for s in tr.spans(r.trace.trace_id))][0]
        names = [s.name for s in tr.spans(victim.trace.trace_id)]
        assert "suspend" in names and "resume" in names
        self._assert_no_leaks(router)
        router.close()
