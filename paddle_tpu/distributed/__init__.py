"""paddle_tpu.distributed — alias of paddle_tpu.parallel (the reference's
import path, python/paddle/distributed/)."""
from ..parallel import *  # noqa: F401,F403
from ..parallel import fleet  # noqa: F401
from ..parallel.collective import ReduceOp  # noqa: F401
from ..parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, spawn, DataParallel)
from ..parallel import sharding  # noqa: F401
from .. import parallel as _parallel
import sys as _sys

# submodule aliases so `import paddle_tpu.distributed.fleet` etc. work
_sys.modules[__name__ + ".fleet"] = fleet
# alias EVERY fleet submodule so both spellings import identically —
# a hand-kept list would let the unaliased ones re-import under the
# distributed name and break their relative imports
import importlib as _importlib
import pkgutil as _pkgutil
for _m in _pkgutil.iter_modules(fleet.__path__):
    _sub = _importlib.import_module(f"{fleet.__name__}.{_m.name}")
    _sys.modules[f"{__name__}.fleet.{_m.name}"] = _sub
from ..parallel import dist_utils as utils
_sys.modules[__name__ + ".utils"] = utils
_sys.modules[__name__ + ".sharding"] = sharding
from ..parallel import collective as _collective  # noqa: E402
_sys.modules[__name__ + ".collective"] = _collective
from ..parallel import auto_parallel  # noqa: E402,F401
from ..parallel.auto_parallel import (  # noqa: E402,F401
    ProcessMesh, shard_tensor, shard_op, reshard)
_sys.modules[__name__ + ".auto_parallel"] = auto_parallel
from . import rpc  # noqa: E402,F401
# reference spelling: paddle.distributed.fleet.auto (Engine lives there)
fleet.auto = auto_parallel
_sys.modules[__name__ + ".fleet.auto"] = auto_parallel
from ..parallel.dist_tail import (  # noqa: E402,F401
    gather, all_gather_object, scatter_object_list,
    broadcast_object_list, alltoall, alltoall_single, isend, irecv,
    ParallelMode, destroy_process_group, is_available, get_backend,
    gloo_init_parallel_env, gloo_barrier, gloo_release, InMemoryDataset,
    QueueDataset, split, CountFilterEntry, ShowClickEntry,
    ProbabilityEntry, io)
_sys.modules[__name__ + ".io"] = io
