"""python -m paddle_tpu.distributed.launch — the multi-host job launcher.

Reference analog: python/paddle/distributed/launch/main.py:18 with the
collective controller (launch/controllers/collective.py), pod/job model
(launch/job/), master rendezvous and elastic restart
(fleet/elastic/manager.py:124).
"""
from .main import main, launch  # noqa: F401
