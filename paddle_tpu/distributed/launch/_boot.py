"""Worker bootstrap for the launch controller.

Two jobs before the user script becomes __main__:
- CPU pinning (when PADDLE_LAUNCH_CPU_DEVICES is set): a TPU PJRT plugin
  can override the JAX_PLATFORMS env var, so pinning must go through the
  jax config API inside the worker process (see device.pin_cpu).
- Liveness heartbeat (when PADDLE_HEARTBEAT_FILE is set): start the beat
  thread the controller's hang watchdog relies on (reference
  fleet/elastic/manager.py keepalive).
"""
import os
import runpy
import sys

if os.environ.get("PADDLE_LAUNCH_CPU_DEVICES"):
    from paddle_tpu.device import pin_cpu
    n = int(os.environ["PADDLE_LAUNCH_CPU_DEVICES"])
    # verify=False: verification would initialize the backend, which must
    # not happen before the worker's jax.distributed.initialize
    if not pin_cpu(n, verify=False):
        print("[launch] could not pin the CPU platform", file=sys.stderr)
        sys.exit(17)

from paddle_tpu.distributed.launch import heartbeat  # noqa: E402

heartbeat.start_from_env()

sys.argv = sys.argv[1:]
runpy.run_path(sys.argv[0], run_name="__main__")
