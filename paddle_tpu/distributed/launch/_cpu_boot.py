"""Worker bootstrap for `launch --devices cpu`.

The environment trap (see device.pin_cpu): a TPU PJRT plugin can override
the JAX_PLATFORMS env var, so pinning the CPU platform must ALSO go through
the jax config API inside the worker process — an env block alone leaves
workers opening the TPU backend. This runner pins, then executes the user
script as __main__.
"""
import os
import runpy
import sys

from paddle_tpu.device import pin_cpu

n = int(os.environ.get("PADDLE_LAUNCH_CPU_DEVICES", "1"))
# verify=False: verification would initialize the backend, which must not
# happen before the worker's jax.distributed.initialize
if not pin_cpu(n, verify=False):
    print("[launch] could not pin the CPU platform", file=sys.stderr)
    sys.exit(17)

sys.argv = sys.argv[1:]
runpy.run_path(sys.argv[0], run_name="__main__")
