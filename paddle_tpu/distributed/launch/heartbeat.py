"""Worker-side liveness heartbeat (reference
fleet/elastic/manager.py:124 — the ElasticManager keeps an etcd lease
alive per worker and the master watches for expiry; here the lease is a
file mtime the local controller watches, no external store needed).

The launch bootstrap calls start_from_env() before the user script runs,
so liveness needs no user code. A worker can call stop() to simulate (or
deliberately signal) loss of liveness — the controller then treats it as
hung and restarts the pod.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

_thread: Optional[threading.Thread] = None
_stop = threading.Event()

ENV_FILE = "PADDLE_HEARTBEAT_FILE"
ENV_INTERVAL = "PADDLE_HEARTBEAT_INTERVAL"
# "1" -> the beat thread is NOT started; only explicit pulse() calls
# touch the lease. With the resilient step loop pulsing per committed
# step, --hang_timeout then measures STEP progress (a hung dispatch goes
# stale even though the process is alive) instead of thread liveness.
ENV_STEP_MODE = "PADDLE_HEARTBEAT_STEP_MODE"

# The elastic-protocol exit code (reference fleet/elastic/manager.py:30
# ELASTIC_EXIT_CODE = 101): a worker exiting with this code is asking the
# launcher for a restart-and-resume (it will reload from the checkpoint
# LATEST pointer), distinct from a crash that burns the failure budget.
# Lives here — not in launch/main or parallel/resilience — because this is
# the one liveness module both the controller and the worker import.
ELASTIC_EXIT_CODE = 101

# Degraded-world handshake riding the exit-101 protocol
# (docs/fault_tolerance.md "Elastic 3D training"): a worker that
# detected device loss writes a world spec JSON to $ENV_WORLD_FILE
# before exiting 101; the launcher reads it and re-exports the spec as
# $ENV_WORLD (re-shaping the CPU virtual device count when the spec
# carries one) so the restarted worker rebuilds its mesh on the
# SURVIVING world instead of assuming the old one. Spec keys (all
# optional): n_devices (int), cpu_devices (int — the launcher's
# --devices cpu re-pin), axes ({axis: degree} — the degraded plan),
# reason (str). Shared contract: both sides import THESE names.
ENV_WORLD_FILE = "PADDLE_TPU_ELASTIC_WORLD_FILE"
ENV_WORLD = "PADDLE_TPU_ELASTIC_WORLD"


def write_world_spec(spec: dict, path: Optional[str] = None
                     ) -> Optional[str]:
    """Atomically (tmp + rename + fsync) write the degraded world spec
    a worker wants its elastic restart to come back with. Returns the
    path written, or None when the launcher did not export the
    contract (then exit-101 restarts on the unchanged world, the
    pre-degrade behavior)."""
    import json
    path = path if path is not None else os.environ.get(ENV_WORLD_FILE)
    if not path:
        return None
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(spec))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_world_spec(path: str) -> Optional[dict]:
    """Parse a world-spec file (None when absent or unparseable — a
    torn spec must degrade to the old-world restart, never crash the
    controller)."""
    import json
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def degraded_world() -> Optional[dict]:
    """The degraded world spec the launcher granted THIS (restarted)
    worker, or None on a fresh/full-world start. The elastic trainer
    consults it before planning so the resumed run plans onto the
    surviving device count (parallel/elastic.py)."""
    import json
    raw = os.environ.get(ENV_WORLD)
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def _touch(path: str) -> None:
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        pass


def start_from_env() -> bool:
    """Start the beat thread if the controller exported the contract;
    idempotent. Returns True when beating."""
    global _thread
    path = os.environ.get(ENV_FILE)
    if not path or (_thread is not None and _thread.is_alive()):
        return _thread is not None
    if os.environ.get(ENV_STEP_MODE) == "1":
        # step mode: the first touch covers boot; after that only
        # pulse() (per committed step) keeps the lease fresh
        _stop.clear()
        _touch(path)
        return True
    interval = float(os.environ.get(ENV_INTERVAL, "1.0"))
    _stop.clear()
    _touch(path)

    def beat():
        while not _stop.wait(interval):
            _touch(path)

    _thread = threading.Thread(target=beat, name="paddle-heartbeat",
                               daemon=True)
    _thread.start()
    return True


def stop() -> None:
    """Stop beating (the controller will see this worker as hung after
    its --hang_timeout)."""
    _stop.set()


_last_pulse = 0.0


def pulse() -> None:
    """Touch the lease file immediately. The resilient step loop calls
    this per completed step; under ENV_STEP_MODE (launcher
    --step_heartbeat) it is the ONLY thing refreshing the lease, so the
    controller's staleness clock tracks step progress directly and a
    hung dispatch trips --hang_timeout even though the process (and the
    default mode's beat thread) is alive. Each pulse publishes the gap
    since the previous one as the `heartbeat_staleness_s` monitor gauge
    — the worker-side view of how close it is sailing to the
    controller's --hang_timeout."""
    global _last_pulse
    import time as _time
    now = _time.time()
    if _last_pulse:
        from ...profiler import monitor
        monitor.gauge("heartbeat_staleness_s").set(now - _last_pulse)
    _last_pulse = now
    path = os.environ.get(ENV_FILE)
    if path and not _stop.is_set():
        _touch(path)
