"""Worker-side liveness heartbeat (reference
fleet/elastic/manager.py:124 — the ElasticManager keeps an etcd lease
alive per worker and the master watches for expiry; here the lease is a
file mtime the local controller watches, no external store needed).

The launch bootstrap calls start_from_env() before the user script runs,
so liveness needs no user code. A worker can call stop() to simulate (or
deliberately signal) loss of liveness — the controller then treats it as
hung and restarts the pod.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

_thread: Optional[threading.Thread] = None
_stop = threading.Event()

ENV_FILE = "PADDLE_HEARTBEAT_FILE"
ENV_INTERVAL = "PADDLE_HEARTBEAT_INTERVAL"


def _touch(path: str) -> None:
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        pass


def start_from_env() -> bool:
    """Start the beat thread if the controller exported the contract;
    idempotent. Returns True when beating."""
    global _thread
    path = os.environ.get(ENV_FILE)
    if not path or (_thread is not None and _thread.is_alive()):
        return _thread is not None
    interval = float(os.environ.get(ENV_INTERVAL, "1.0"))
    _stop.clear()
    _touch(path)

    def beat():
        while not _stop.wait(interval):
            _touch(path)

    _thread = threading.Thread(target=beat, name="paddle-heartbeat",
                               daemon=True)
    _thread.start()
    return True


def stop() -> None:
    """Stop beating (the controller will see this worker as hung after
    its --hang_timeout)."""
    _stop.set()
