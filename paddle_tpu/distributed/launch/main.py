"""Launcher CLI (reference launch/main.py:18 + controllers/collective.py).

TPU-native process model: one process per HOST (JAX single-controller),
not one per accelerator — a v5p-16 pod slice with 4 hosts is
`--nnodes 4`, each host process sees its 4 local chips and
`jax.distributed.initialize` federates them. The launcher:

- on a single node (`--nnodes 1`, the default) can still spawn N local
  processes with a virtual CPU mesh for testing multi-process rendezvous
  (`--nproc_per_node N --devices cpu`) — the reference's
  single-node-multi-proc dev loop;
- exports the PADDLE_* env contract consumed by parallel/env.py
  (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER), mirroring the
  reference's env contract;
- elastic-lite: `--max_restart K` watches children and restarts the whole
  local pod up to K times when any worker exits nonzero (the reference
  ElasticManager's restart loop, minus etcd — the coordination service
  owns membership).

Usage:
  python -m paddle_tpu.distributed.launch --nnodes 2 --node_rank 0 \
      --master 10.0.0.1:12355 train.py --my-args ...
  python -m paddle_tpu.distributed.launch --nproc_per_node 2 \
      --devices cpu smoke.py
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-host launcher (reference launch/main.py)")
    p.add_argument("--nnodes", type=int, default=int(
        os.environ.get("PADDLE_NNODES", "1")),
        help="number of hosts in the job")
    p.add_argument("--node_rank", type=int, default=int(
        os.environ.get("PADDLE_NODE_RANK", "0")),
        help="this host's rank [0, nnodes)")
    p.add_argument("--master", default=os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:12355"),
        help="coordinator address host:port (rank-0 host)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="local worker processes (1 for TPU hosts; >1 only "
                        "for CPU-mesh testing)")
    p.add_argument("--devices", default=None,
                   help="'cpu' forces the CPU platform with a virtual "
                        "device count per proc (testing)")
    p.add_argument("--cpus_per_proc", type=int, default=1,
                   help="virtual CPU devices per process when "
                        "--devices cpu")
    p.add_argument("--max_restart", type=int, default=0,
                   help="elastic-lite: restart the local pod up to K "
                        "times on worker failure")
    p.add_argument("--log_dir", default=None,
                   help="write per-worker logs under this dir")
    p.add_argument("--run_mode", default="collective",
                   help="collective (the only mode; ps is descoped)")
    p.add_argument("training_script", help="entry script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank: int) -> dict:
    """The PADDLE_* env contract (reference launch/controllers/collective.py
    builds the same block per worker)."""
    nprocs = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    host, port = args.master.rsplit(":", 1)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": args.master,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_NODE_RANK": str(args.node_rank),
        # torch-style aliases (env.py accepts both)
        "RANK": str(rank),
        "WORLD_SIZE": str(nprocs),
        "MASTER_ADDR": host,
        "MASTER_PORT": port,
    })
    if args.devices == "cpu":
        from ...device import cpu_pin_env
        env = cpu_pin_env(args.cpus_per_proc, base_env=env)
        env["PADDLE_LAUNCH_CPU_DEVICES"] = str(args.cpus_per_proc)
    return env


def _spawn(args) -> List[subprocess.Popen]:
    procs = []
    for lr in range(args.nproc_per_node):
        out = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(
                args.log_dir,
                f"worker.{args.node_rank}.{lr}.log"), "ab")
        try:
            procs.append(_popen(args, lr, out))
        finally:
            if out is not None:
                out.close()          # the child inherited the fd
    return procs


def _popen(args, lr, out):
    if args.devices == "cpu":
        # route through the pin-then-run bootstrap: a TPU PJRT plugin
        # can override JAX_PLATFORMS, so the CPU pin must happen
        # in-process (see _cpu_boot / device.pin_cpu)
        cmd = [sys.executable, "-m",
               "paddle_tpu.distributed.launch._cpu_boot",
               args.training_script, *args.training_script_args]
    else:
        cmd = [sys.executable, args.training_script,
               *args.training_script_args]
    return subprocess.Popen(
        cmd, env=_worker_env(args, lr), stdout=out,
        stderr=subprocess.STDOUT if out else None)


def _terminate(procs: List[subprocess.Popen]):
    """SIGTERM then escalate to SIGKILL: a worker wedged in backend init
    can mask/ignore SIGTERM and would otherwise orphan, holding the
    coordinator port."""
    for pr in procs:
        pr.send_signal(signal.SIGTERM)
    for pr in procs:
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()


def _wait(procs: List[subprocess.Popen]) -> Optional[int]:
    """Wait for all workers; on first nonzero exit, kill the rest and
    return that code (the collective controller's fail-fast). Returns
    None on KeyboardInterrupt — distinct from any worker exit code."""
    try:
        while procs:
            for pr in list(procs):
                rc = pr.poll()
                if rc is None:
                    continue
                procs.remove(pr)
                if rc != 0:
                    _terminate(procs)
                    return rc
            time.sleep(0.2)
        return 0
    except KeyboardInterrupt:
        _terminate(procs)
        return None


def launch(argv: Optional[List[str]] = None) -> int:
    """Programmatic entry (returns the job's exit code)."""
    args = _parse_args(argv)
    attempt = 0
    while True:
        if attempt:
            print(f"[launch] elastic restart {attempt}/{args.max_restart}",
                  file=sys.stderr, flush=True)
        rc = _wait(_spawn(args))
        if rc == 0:
            return 0
        if rc is None:
            # launcher-level interrupt is not a worker failure — never
            # restart it (a worker's own exit 130 still restarts)
            return 130
        if attempt >= args.max_restart:
            print(f"[launch] workers failed (rc={rc}); restarts exhausted",
                  file=sys.stderr, flush=True)
            return rc
        attempt += 1


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
