"""Launcher CLI (reference launch/main.py:18 + controllers/collective.py).

TPU-native process model: one process per HOST (JAX single-controller),
not one per accelerator — a v5p-16 pod slice with 4 hosts is
`--nnodes 4`, each host process sees its 4 local chips and
`jax.distributed.initialize` federates them. The launcher:

- on a single node (`--nnodes 1`, the default) can still spawn N local
  processes with a virtual CPU mesh for testing multi-process rendezvous
  (`--nproc_per_node N --devices cpu`) — the reference's
  single-node-multi-proc dev loop;
- exports the PADDLE_* env contract consumed by parallel/env.py
  (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER), mirroring the
  reference's env contract;
- elastic-lite: `--max_restart K` watches children and restarts the whole
  local pod up to K times when any worker exits nonzero (the reference
  ElasticManager's restart loop, minus etcd — the coordination service
  owns membership);
- liveness (reference fleet/elastic/manager.py:124): with
  `--hang_timeout S` each worker heartbeats a file through the boot shim
  and the controller restarts the pod when any worker's beat goes stale —
  hung workers (deadlock, wedged backend init), not just exited ones;
- scale-down continuation: `--min_procs M` lets the pod relaunch with
  one fewer worker (down to M) after restarts are exhausted — the
  reference's nnodes-1 "job proceeds after grace period" behavior, with
  the world size re-exported so rendezvous re-forms at the smaller size.

Usage:
  python -m paddle_tpu.distributed.launch --nnodes 2 --node_rank 0 \
      --master 10.0.0.1:12355 train.py --my-args ...
  python -m paddle_tpu.distributed.launch --nproc_per_node 2 \
      --devices cpu smoke.py
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from .heartbeat import (ELASTIC_EXIT_CODE, ENV_WORLD, ENV_WORLD_FILE,
                        read_world_spec)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-host launcher (reference launch/main.py)")
    p.add_argument("--nnodes", type=int, default=int(
        os.environ.get("PADDLE_NNODES", "1")),
        help="number of hosts in the job")
    p.add_argument("--node_rank", type=int, default=int(
        os.environ.get("PADDLE_NODE_RANK", "0")),
        help="this host's rank [0, nnodes)")
    p.add_argument("--master", default=os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:12355"),
        help="coordinator address host:port (rank-0 host)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="local worker processes (1 for TPU hosts; >1 only "
                        "for CPU-mesh testing)")
    p.add_argument("--devices", default=None,
                   help="'cpu' forces the CPU platform with a virtual "
                        "device count per proc (testing)")
    p.add_argument("--cpus_per_proc", type=int, default=1,
                   help="virtual CPU devices per process when "
                        "--devices cpu")
    p.add_argument("--max_restart", type=int, default=0,
                   help="elastic-lite: restart the local pod up to K "
                        "times on worker failure")
    p.add_argument("--hang_timeout", type=float, default=0.0,
                   help="liveness: treat a worker as failed when its "
                        "heartbeat file is older than this many seconds "
                        "(0 disables the watchdog)")
    p.add_argument("--heartbeat_interval", type=float, default=1.0,
                   help="worker heartbeat period when --hang_timeout is "
                        "set")
    p.add_argument("--step_heartbeat", action="store_true",
                   help="liveness tracks STEP progress: no background "
                        "beat thread; only the resilient step loop's "
                        "per-step pulse refreshes the lease, so a hung "
                        "dispatch goes stale after --hang_timeout even "
                        "while the process lives (size the timeout for "
                        "boot + compile + slowest step)")
    p.add_argument("--max_elastic_restart", type=int, default=16,
                   help="restarts granted to workers that exit with the "
                        "elastic protocol code "
                        f"({ELASTIC_EXIT_CODE}: 'restart me, I will "
                        "resume from my checkpoint') — budgeted "
                        "separately from --max_restart crash restarts")
    p.add_argument("--min_procs", type=int, default=0,
                   help="scale-down floor: after restarts are exhausted, "
                        "relaunch with one fewer local worker down to "
                        "this count (0 disables scale-down)")
    p.add_argument("--scale_grace", type=float, default=3.0,
                   help="grace period before a scaled-down relaunch")
    p.add_argument("--log_dir", default=None,
                   help="write per-worker logs under this dir")
    p.add_argument("--run_mode", default="collective",
                   help="collective (the only mode; ps is descoped)")
    p.add_argument("training_script", help="entry script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank: int) -> dict:
    """The PADDLE_* env contract (reference launch/controllers/collective.py
    builds the same block per worker)."""
    nprocs = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    host, port = args.master.rsplit(":", 1)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": args.master,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_NODE_RANK": str(args.node_rank),
        # torch-style aliases (env.py accepts both)
        "RANK": str(rank),
        "WORLD_SIZE": str(nprocs),
        "MASTER_ADDR": host,
        "MASTER_PORT": port,
    })
    if args.devices == "cpu":
        from ...device import cpu_pin_env
        env = cpu_pin_env(args.cpus_per_proc, base_env=env)
        env["PADDLE_LAUNCH_CPU_DEVICES"] = str(args.cpus_per_proc)
    # degraded-world handshake (heartbeat.py): the worker writes its
    # wanted world spec here before an elastic exit; the launcher reads
    # it back in launch() and re-exports it to the restarted pod
    env.setdefault(ENV_WORLD_FILE,
                   os.path.join(_hb_dir(args), "elastic_world.json"))
    granted = getattr(args, "_elastic_world", None)
    if granted:
        env[ENV_WORLD] = granted
    # crash flight recorder (profiler/flight_recorder.py): every worker
    # gets a dump directory so a dead pod leaves a black box the operator
    # (and tools/chaos_drill.py) can read — an explicit
    # PADDLE_TPU_FLIGHT_DIR in the caller's env wins
    if "PADDLE_TPU_FLIGHT_DIR" not in env:
        env["PADDLE_TPU_FLIGHT_DIR"] = os.path.join(_hb_dir(args), "flight")
    return env


class _Worker:
    """One spawned worker + the liveness state the watchdog tracks."""

    def __init__(self, proc: subprocess.Popen, hb_path: Optional[str]):
        self.proc = proc
        self.hb_path = hb_path
        self.started = time.time()

    def stale_for(self) -> float:
        """Seconds since the last heartbeat (spawn time counts as the
        first beat, so slow boots are not misread as hangs)."""
        last = self.started
        if self.hb_path:
            try:
                last = max(last, os.stat(self.hb_path).st_mtime)
            except OSError:
                pass
        return time.time() - last


def _hb_dir(args) -> str:
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        return args.log_dir
    import tempfile
    d = getattr(args, "_hb_tmp", None)
    if d is None:
        d = tempfile.mkdtemp(prefix="paddle_launch_hb_")
        args._hb_tmp = d
    return d


def _spawn(args) -> List[_Worker]:
    workers = []
    for lr in range(args.nproc_per_node):
        out = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(
                args.log_dir,
                f"worker.{args.node_rank}.{lr}.log"), "ab")
        try:
            workers.append(_popen(args, lr, out))
        finally:
            if out is not None:
                out.close()          # the child inherited the fd
    return workers


def _popen(args, lr, out) -> _Worker:
    env = _worker_env(args, lr)
    hb_path = None
    if args.hang_timeout > 0:
        hb_path = os.path.join(
            _hb_dir(args), f"hb.{args.node_rank}.{lr}")
        try:                         # fresh lease per (re)spawn
            os.remove(hb_path)
        except OSError:
            pass
        env["PADDLE_HEARTBEAT_FILE"] = hb_path
        env["PADDLE_HEARTBEAT_INTERVAL"] = str(args.heartbeat_interval)
        if args.step_heartbeat:
            env["PADDLE_HEARTBEAT_STEP_MODE"] = "1"
    if args.devices == "cpu" or hb_path:
        # route through the bootstrap: the CPU pin must happen in-process
        # (a TPU PJRT plugin can override JAX_PLATFORMS — see
        # device.pin_cpu) and the heartbeat thread must start before the
        # user script (see heartbeat.py)
        cmd = [sys.executable, "-m",
               "paddle_tpu.distributed.launch._boot",
               args.training_script, *args.training_script_args]
    else:
        cmd = [sys.executable, args.training_script,
               *args.training_script_args]
    proc = subprocess.Popen(
        cmd, env=env, stdout=out,
        stderr=subprocess.STDOUT if out else None)
    return _Worker(proc, hb_path)


def _terminate(workers: List[_Worker]):
    """SIGTERM then escalate to SIGKILL: a worker wedged in backend init
    can mask/ignore SIGTERM and would otherwise orphan, holding the
    coordinator port."""
    for w in workers:
        w.proc.send_signal(signal.SIGTERM)
    for w in workers:
        try:
            w.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            w.proc.kill()


# _wait's sentinel for "a worker stopped heartbeating": distinct from any
# real exit code so launch() can log the right reason
HUNG = -257


def _wait(workers: List[_Worker], hang_timeout: float = 0.0) \
        -> Optional[int]:
    """Wait for all workers; on first nonzero exit, kill the rest and
    return that code (the collective controller's fail-fast). With
    hang_timeout > 0 a worker whose heartbeat goes stale counts as failed
    (returns HUNG). Returns None on KeyboardInterrupt — distinct from any
    worker exit code."""
    try:
        while workers:
            for w in list(workers):
                rc = w.proc.poll()
                if rc is None:
                    if hang_timeout > 0 and w.stale_for() > hang_timeout:
                        print(f"[launch] worker pid={w.proc.pid} hung "
                              f"(no heartbeat for "
                              f"{w.stale_for():.1f}s); restarting pod",
                              file=sys.stderr, flush=True)
                        _terminate(workers)
                        return HUNG
                    continue
                workers.remove(w)
                if rc != 0:
                    _terminate(workers)
                    return rc
            time.sleep(0.2)
        return 0
    except KeyboardInterrupt:
        _terminate(workers)
        return None


def launch(argv: Optional[List[str]] = None) -> int:
    """Programmatic entry (returns the job's exit code)."""
    # controller-side observability: phase spans + restart counters
    # (import-light — profiler/monitor pulls in no jax); the worker-side
    # black box is env-wired in _worker_env
    from ...profiler import RecordEvent, monitor
    from ...profiler import flight_recorder
    mon_restart = monitor.counter("launch_pod_restart")
    mon_elastic = monitor.counter("launch_elastic_restart")
    mon_hung = monitor.counter("launch_hung_worker")
    mon_scale = monitor.counter("launch_scale_down")
    args = _parse_args(argv)
    attempt = 0
    elastic = 0
    while True:
        if attempt:
            # crash-budget restarts; rc=ELASTIC_EXIT_CODE restarts print
            # their own distinctly-worded line below
            print(f"[launch] pod restart {attempt}/{args.max_restart} "
                  f"(crash budget)", file=sys.stderr, flush=True)
        with RecordEvent("launch.spawn"):
            workers = _spawn(args)
        with RecordEvent("launch.wait"):
            rc = _wait(workers, args.hang_timeout)
        flight_recorder.note(phase="pod_exit", rc=rc, attempt=attempt,
                             elastic=elastic)
        if rc == HUNG:
            mon_hung.add()
        if rc == 0:
            return 0
        if rc is None:
            # launcher-level interrupt is not a worker failure — never
            # restart it (a worker's own exit 130 still restarts)
            return 130
        if rc == ELASTIC_EXIT_CODE and elastic < args.max_elastic_restart:
            # the worker ASKED for this restart (resilience watchdog: a
            # hung step it will recover from by resuming at the LATEST
            # snapshot) — reference ELASTIC_EXIT_CODE=101 protocol,
            # fleet/elastic/manager.py:30. Budgeted separately so tunnel
            # flaps don't consume the crash-restart budget.
            elastic += 1
            mon_elastic.add()
            # degraded-world handshake: a worker that lost devices
            # leaves a world spec (heartbeat.write_world_spec) naming
            # the SURVIVING world; the restarted pod must not assume
            # the old one. The spec re-exports as $PADDLE_TPU_ELASTIC_
            # WORLD to every later spawn, and a cpu_devices entry
            # re-shapes the virtual CPU platform (the --devices cpu
            # simulation of a physically smaller slice).
            wpath = os.environ.get(ENV_WORLD_FILE) or os.path.join(
                _hb_dir(args), "elastic_world.json")
            spec = read_world_spec(wpath)
            if spec is not None:
                import json as _json
                args._elastic_world = _json.dumps(spec)
                try:            # consumed: one spec per elastic exit
                    os.remove(wpath)
                except OSError:
                    pass
                if args.devices == "cpu" and spec.get("cpu_devices"):
                    args.cpus_per_proc = int(spec["cpu_devices"])
                mon_degraded = monitor.counter("launch_degraded_world")
                mon_degraded.add()
                print(f"[launch] elastic restart carries a DEGRADED "
                      f"world spec: {spec}", file=sys.stderr, flush=True)
            print(f"[launch] worker requested elastic restart "
                  f"({elastic}/{args.max_elastic_restart}, "
                  f"rc={ELASTIC_EXIT_CODE})", file=sys.stderr, flush=True)
            continue
        if attempt >= args.max_restart:
            if (args.min_procs > 0
                    and args.nnodes == 1
                    and args.nproc_per_node - 1 >= args.min_procs):
                # single-node only: shrinking one host's proc count in a
                # multi-node job would desync WORLD_SIZE/rank bases across
                # hosts — true multi-node membership changes belong to the
                # coordination service (reference: etcd in
                # fleet/elastic/manager.py)
                # scale-down continuation (reference elastic manager's
                # "nnodes-1 proceeds after the grace window"): re-form
                # the pod one worker smaller; the env contract re-exports
                # the reduced world size so rendezvous matches
                args.nproc_per_node -= 1
                attempt = 0
                mon_scale.add()
                print(f"[launch] restarts exhausted (rc={rc}); scaling "
                      f"down to {args.nproc_per_node} workers after "
                      f"{args.scale_grace}s grace",
                      file=sys.stderr, flush=True)
                time.sleep(args.scale_grace)
                continue
            print(f"[launch] workers failed (rc={rc}); restarts exhausted",
                  file=sys.stderr, flush=True)
            # the job is dying: leave the CONTROLLER's black box (pod
            # exit history + restart counters) beside the workers' dumps
            flight_recorder.recorder().set_dir(
                os.environ.get("PADDLE_TPU_FLIGHT_DIR")
                or os.path.join(_hb_dir(args), "flight"))
            flight_recorder.dump("launch_failed")
            return 1 if rc == HUNG else rc
        attempt += 1
        mon_restart.add()


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
