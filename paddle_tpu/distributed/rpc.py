"""paddle_tpu.distributed.rpc — remote procedure calls between workers.

Reference analog: python/paddle/distributed/rpc (init_rpc over a TCP
master, rpc_sync/rpc_async executing a python callable on a named remote
worker, WorkerInfo registry, shutdown barrier). The reference rides
brpc+protobuf; TPU-native there is nothing accelerator-specific about the
control plane, so this is a dependency-free TCP implementation: one
length-prefixed-pickle server thread per worker, a rank-0 master that
collects (name, addr) registrations and publishes the worker table, and
concurrent.futures for the async surface.

Security note (same trust model as the reference): payloads are pickled
python callables — only ever bind these endpoints inside a trusted
training cluster.

Host-side only: callables run in the worker's interpreter; anything
jax-valued they return is pulled to numpy before the wire.
"""
from __future__ import annotations

import concurrent.futures
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

_LEN = struct.Struct("!Q")


class WorkerInfo:
    def __init__(self, name: str, rank: int, ip: str, port: int):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name!r}, rank={self.rank}, "
                f"ip={self.ip!r}, port={self.port})")


class _State:
    def __init__(self):
        self.name: Optional[str] = None
        self.rank = -1
        self.world_size = 0
        self.workers: Dict[str, WorkerInfo] = {}
        self.server: Optional[socket.socket] = None
        self.server_thread: Optional[threading.Thread] = None
        self.master_thread: Optional[threading.Thread] = None
        self.pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self.shutting_down = False


_state = _State()


def _send_msg(sock: socket.socket, obj: Any) -> None:
    # cloudpickle serializes lambdas/closures by value (the reference's
    # rpc also ships callables this way); stdlib pickle.loads reads its
    # output fine on the other side
    try:
        import cloudpickle
        data = cloudpickle.dumps(obj, protocol=4)
    except Exception:
        data = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def _to_host(obj):
    """Pull jax/Tensor values to numpy before pickling onto the wire."""
    import numpy as np
    if hasattr(obj, "numpy") and callable(obj.numpy):
        return np.asarray(obj.numpy())
    if type(obj).__module__.startswith("jaxlib"):
        return np.asarray(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    return obj


# ------------------------------------------------------------------ server
def _serve_conn(conn: socket.socket):
    try:
        while True:
            try:
                msg = _recv_msg(conn)
            except (ConnectionError, OSError):
                return
            kind = msg[0]
            if kind == "call":
                _, fn, args, kwargs = msg
                try:
                    out = fn(*args, **kwargs)
                    _send_msg(conn, ("ok", _to_host(out)))
                except BaseException as e:  # propagate to caller
                    import traceback
                    _send_msg(conn, ("err", repr(e),
                                     traceback.format_exc()))
            elif kind == "ping":
                _send_msg(conn, ("ok", None))
            elif kind == "bye":
                return
    finally:
        conn.close()


def _server_loop(srv: socket.socket):
    while not _state.shutting_down:
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        threading.Thread(target=_serve_conn, args=(conn,),
                         daemon=True).start()


# ------------------------------------------------------------------ master
def _master_loop(msock: socket.socket, world_size: int):
    """Rank-0 registration service: collect world_size (name, rank, addr)
    entries, then answer the full table to each registrant. A stray
    connection (port scan, health check, worker dying mid-register) must
    not stall or kill the rendezvous: each registration recv is bounded
    and failures just drop that connection."""
    entries: Dict[int, WorkerInfo] = {}
    conns: List[socket.socket] = []
    while len(entries) < world_size:
        conn, _ = msock.accept()
        try:
            conn.settimeout(10.0)
            msg = _recv_msg(conn)
            if not (isinstance(msg, tuple) and msg
                    and msg[0] == "register"):
                conn.close()
                continue
            _, name, rank, ip, port = msg
            conn.settimeout(None)
        except Exception:
            conn.close()
            continue
        entries[rank] = WorkerInfo(name, rank, ip, port)
        conns.append(conn)
    table = {wi.name: wi for wi in entries.values()}
    for conn in conns:
        try:
            _send_msg(conn, ("table", table))
        except OSError:
            pass
        finally:
            conn.close()
    msock.close()


# ------------------------------------------------------------------ api
def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Join the RPC group (reference rpc.init_rpc). Blocks until all
    world_size workers registered with the master (rank 0 hosts it)."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:29511")
    mhost, _, mport = master_endpoint.partition(":")
    mport = int(mport)

    # worker server on an ephemeral port
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    _state.server = srv
    _state.shutting_down = False
    _state.server_thread = threading.Thread(
        target=_server_loop, args=(srv,), daemon=True)
    _state.server_thread.start()

    if rank == 0:
        msock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        msock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        msock.bind((mhost if mhost else "0.0.0.0", mport))
        msock.listen(world_size + 8)
        _state.master_thread = threading.Thread(
            target=_master_loop, args=(msock, world_size), daemon=True)
        _state.master_thread.start()

    # register and receive the table (retry while the master comes up)
    deadline = time.time() + 60.0
    while True:
        try:
            c = socket.create_connection((mhost or "127.0.0.1", mport),
                                         timeout=5.0)
            break
        except OSError:
            if time.time() > deadline:
                raise TimeoutError(
                    f"rpc master {master_endpoint} unreachable")
            time.sleep(0.05)
    ip = c.getsockname()[0]
    # the table only arrives once ALL workers registered: lift the 5s
    # connect timeout so normal multi-host startup skew doesn't kill the
    # early registrants mid-recv
    c.settimeout(max(5.0, deadline - time.time()))
    _send_msg(c, ("register", name, rank, ip, port))
    kind, table = _recv_msg(c)
    assert kind == "table"
    c.close()

    _state.name = name
    _state.rank = rank
    _state.world_size = world_size
    _state.workers = table
    _state.pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=max(4, world_size))


def get_current_worker_info() -> WorkerInfo:
    _require_init()
    return _state.workers[_state.name]


def get_worker_info(name: str) -> WorkerInfo:
    _require_init()
    return _state.workers[name]


def get_all_worker_infos() -> List[WorkerInfo]:
    _require_init()
    return sorted(_state.workers.values(), key=lambda w: w.rank)


def _require_init():
    if not _state.workers:
        raise RuntimeError("call paddle_tpu.distributed.rpc.init_rpc first")


class _RemoteError(RuntimeError):
    pass


def _call(to: str, fn, args, kwargs, timeout):
    _require_init()
    wi = _state.workers[to]
    with socket.create_connection((wi.ip, wi.port),
                                  timeout=timeout or None) as c:
        if timeout:
            c.settimeout(timeout)
        _send_msg(c, ("call", fn, tuple(args or ()), dict(kwargs or {})))
        msg = _recv_msg(c)
    if msg[0] == "ok":
        return msg[1]
    raise _RemoteError(
        f"rpc to {to!r} failed: {msg[1]}\nremote traceback:\n{msg[2]}")


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=None):
    """Execute fn(*args, **kwargs) on worker `to`, return its result
    (reference rpc.rpc_sync)."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=None):
    """Async variant: returns a concurrent.futures.Future with .wait()
    aliasing .result() (the reference FutureWrapper surface)."""
    _require_init()
    fut = _state.pool.submit(_call, to, fn, args, kwargs, timeout)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result  # paddle's Future spells it wait()
    return fut


def shutdown():
    """Drain and leave the group (reference rpc.shutdown). Barrier-free by
    design: each worker closes its own server; in-flight calls finish on
    their connection threads."""
    if _state.pool is not None:
        _state.pool.shutdown(wait=True)
        _state.pool = None
    _state.shutting_down = True
    if _state.server is not None:
        try:
            _state.server.close()
        except OSError:
            pass
        _state.server = None
    _state.workers = {}
    _state.name = None
