"""paddle_tpu.pir — the IR surface.

Reference analog: paddle/pir/ + paddle/fluid/pir/ (the new IR: typed ops in
SSA form, translated from ProgramDesc by translate_to_pir, lowered by
pass pipelines). TPU-native collapse: the SSA IR of record here is the
jaxpr → StableHLO pipeline jax/XLA already maintains — this module makes
it inspectable at the paddle API shape instead of re-implementing an IR.

- translate_to_pir(program) → the composed jaxpr of a static Program
  (paddle_tpu.static.Program), i.e. what the reference's
  ProgramDesc→pir translator produces: one SSA module for the graph.
- get_jaxpr(fn, *args) / get_stablehlo(fn, *args) — the same two levels
  for any jax-traceable callable (jit.to_static'ed models included).
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np


def translate_to_pir(program=None):
    """Compose a static Program's recorded ops into one function and
    return its ClosedJaxpr — the SSA-form IR of the whole graph
    (reference pir::Program from translate_to_pir). str() it for the
    textual form."""
    from .static.program import (default_main_program, _replay,
                                 _replay_guard)
    program = program or default_main_program()
    block = program.global_block()

    feed_vars = [v for v in block.vars.values() if v.is_feed]
    param_vars = [v for v in block.vars.values() if v.is_parameter]
    names = [v.name for v in feed_vars + param_vars]
    # dynamic dims (per the Variable's authoritative _dyn_dims, NOT the
    # sentinel value — a real size-97 dim stays 97) trace at a nominal 8
    avals = [jax.ShapeDtypeStruct(
        tuple(8 if i in v._dyn_dims else s
              for i, s in enumerate(v._value.shape)),
        v._value.dtype) for v in feed_vars + param_vars]

    def composed(*vals):
        env = dict(zip(names, vals))
        with _replay_guard():
            _replay(block, env)
        outs = [env[nm] for op in block.ops for nm in op.out_names
                if nm in env]
        return outs[-1] if outs else ()

    return jax.make_jaxpr(composed)(*avals)


def get_jaxpr(fn, *example_args, **kwargs):
    """ClosedJaxpr of any jax-traceable callable (the tier below
    StableHLO; reference analog: the pir program before lowering)."""
    return jax.make_jaxpr(fn, **kwargs)(*example_args)


def get_stablehlo(fn, *example_args) -> str:
    """StableHLO text of the lowered computation — the serialized,
    versioned IR (what paddle_tpu.jit.save persists)."""
    return jax.jit(fn).lower(*example_args).as_text()


def core_uses_pir() -> bool:
    """Reference paddle.base.framework.in_pir_mode analog: the jaxpr/
    StableHLO pipeline is always on."""
    return True


# --------------------------------------------------------------------------
# Pass surface (reference paddle/ir/pass/pass_manager.h + pass.h): a
# user-visible transform seam over the recorded static Program. XLA owns
# the heavy optimization of the lowered graph; these passes act one level
# up, on the Program's op list — the tier the reference's pir passes
# (dead-code elimination, constant folding) operate on.
# --------------------------------------------------------------------------

class Pass:
    """Base pass (reference pir::Pass): subclass and implement
    apply(program) -> stats dict."""

    name = "pass"

    def apply(self, program) -> dict:                # pragma: no cover
        raise NotImplementedError

    def __repr__(self):
        return f"<pir.Pass {self.name}>"


def _live_set(block, outputs):
    """Transitive closure of ops needed for `outputs` (names)."""
    live = set(outputs)
    needed = []
    for node in reversed(block.ops):
        if any(nm in live for nm in node.out_names):
            needed.append(node)
            live.update(node.input_names())
    needed.reverse()
    return needed


class DeadCodeEliminationPass(Pass):
    """Drop ops not needed for the graph outputs (reference
    dead_code_elimination_pass.cc). `outputs` names the fetch set; when
    omitted, the last op's outputs are taken as the graph result (the
    same convention translate_to_pir uses)."""

    name = "dead_code_elimination"

    def __init__(self, outputs=None):
        self.outputs = list(outputs) if outputs else None

    def apply(self, program) -> dict:
        block = program.global_block()
        if not block.ops:
            return {"removed": 0}
        outs = self.outputs or list(block.ops[-1].out_names)
        before = len(block.ops)
        block.ops[:] = _live_set(block, outs)
        removed = before - len(block.ops)
        if removed:
            # only a real change invalidates the Executor's compiled cache
            program._version += 1
        return {"removed": removed}


class ConstantFoldingPass(Pass):
    """Precompute ops whose inputs are all baked literals (reference
    constant_folding_pass.cc). The node is replaced by a zero-input node
    returning the folded arrays — downstream refs are untouched, and
    under the Executor's jit composition the values become XLA
    constants."""

    name = "constant_folding"

    # never folded: nondeterministic or stateful op families
    _SKIP = ("dropout", "random", "gaussian", "uniform", "bernoulli",
             "randint", "poisson", "multinomial", "exponential",
             "dirichlet", "shuffle", "while_loop", "all_reduce",
             "all_gather", "broadcast", "reduce_scatter", "send", "recv")

    def apply(self, program) -> dict:
        from .static.program import OpNode
        block = program.global_block()
        folded = 0
        for i, node in enumerate(list(block.ops)):
            if node.input_names():
                continue
            if any(s in node.type for s in self._SKIP):
                continue
            try:
                args = [a.v for a in node.arg_plan]
                out = node.fn(*args, **node.attrs)
            except Exception:
                continue                      # leave unfoldable ops alone
            outs = tuple(out) if isinstance(out, (tuple, list)) else (out,)

            def const_fn(*_a, _outs=outs):
                return _outs if len(_outs) > 1 else _outs[0]

            block.ops[i] = OpNode(f"pir.folded::{node.type}", const_fn,
                                  [], {}, node.out_names)
            folded += 1
        if folded:
            program._version += 1
        return {"folded": folded}


_PASS_REGISTRY = {
    DeadCodeEliminationPass.name: DeadCodeEliminationPass,
    ConstantFoldingPass.name: ConstantFoldingPass,
}


def register_pass(name: str, cls=None):
    """Register a custom pass class (reference REGISTER_IR_PASS)."""
    if cls is None:
        def deco(c):
            _PASS_REGISTRY[name] = c
            return c
        return deco
    _PASS_REGISTRY[name] = cls
    return cls


class PassManager:
    """Ordered pass pipeline (reference pir::PassManager). add_pass by
    registered name (kwargs forwarded) or instance; run(program) applies
    in order and records per-pass statistics."""

    def __init__(self, passes=None):
        self._passes = []
        self.stats = []
        self._print_ir = False
        for p in passes or []:
            self.add_pass(p)

    def add_pass(self, p, **kwargs) -> "PassManager":
        if isinstance(p, str):
            if p not in _PASS_REGISTRY:
                raise ValueError(
                    f"unknown pass {p!r}; registered: "
                    f"{sorted(_PASS_REGISTRY)}")
            p = _PASS_REGISTRY[p](**kwargs)
        self._passes.append(p)
        return self

    @property
    def passes(self):
        return [p.name for p in self._passes]

    def enable_ir_printing(self):
        self._print_ir = True
        return self

    def run(self, program=None) -> list:
        from .static.program import default_main_program
        program = program or default_main_program()
        self.stats = []
        for p in self._passes:
            if self._print_ir:
                print(f"// ===== before {p.name} =====\n"
                      f"{program_to_string(program)}")
            st = p.apply(program)
            self.stats.append({"pass": p.name, **(st or {})})
            if self._print_ir:
                print(f"// ===== after {p.name} =====\n"
                      f"{program_to_string(program)}")
        return self.stats

    def __len__(self):
        return len(self._passes)


def program_to_string(program) -> str:
    """Textual form of a Program's op list (reference Program::Print)."""
    block = program.global_block()
    lines = []
    for node in block.ops:
        ins = ", ".join(node.input_names())
        outs = ", ".join(node.out_names)
        attrs = f" {{{node.attrs}}}" if node.attrs else ""
        lines.append(f"  ({outs}) = \"{node.type}\"({ins}){attrs}")
    return "{\n" + "\n".join(lines) + "\n}"
