"""paddle_tpu.pir — the IR surface.

Reference analog: paddle/pir/ + paddle/fluid/pir/ (the new IR: typed ops in
SSA form, translated from ProgramDesc by translate_to_pir, lowered by
pass pipelines). TPU-native collapse: the SSA IR of record here is the
jaxpr → StableHLO pipeline jax/XLA already maintains — this module makes
it inspectable at the paddle API shape instead of re-implementing an IR.

- translate_to_pir(program) → the composed jaxpr of a static Program
  (paddle_tpu.static.Program), i.e. what the reference's
  ProgramDesc→pir translator produces: one SSA module for the graph.
- get_jaxpr(fn, *args) / get_stablehlo(fn, *args) — the same two levels
  for any jax-traceable callable (jit.to_static'ed models included).
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np


def translate_to_pir(program=None):
    """Compose a static Program's recorded ops into one function and
    return its ClosedJaxpr — the SSA-form IR of the whole graph
    (reference pir::Program from translate_to_pir). str() it for the
    textual form."""
    from .static.program import (default_main_program, _replay,
                                 _replay_guard)
    program = program or default_main_program()
    block = program.global_block()

    feed_vars = [v for v in block.vars.values() if v.is_feed]
    param_vars = [v for v in block.vars.values() if v.is_parameter]
    names = [v.name for v in feed_vars + param_vars]
    # dynamic dims (per the Variable's authoritative _dyn_dims, NOT the
    # sentinel value — a real size-97 dim stays 97) trace at a nominal 8
    avals = [jax.ShapeDtypeStruct(
        tuple(8 if i in v._dyn_dims else s
              for i, s in enumerate(v._value.shape)),
        v._value.dtype) for v in feed_vars + param_vars]

    def composed(*vals):
        env = dict(zip(names, vals))
        with _replay_guard():
            _replay(block, env)
        outs = [env[nm] for op in block.ops for nm in op.out_names
                if nm in env]
        return outs[-1] if outs else ()

    return jax.make_jaxpr(composed)(*avals)


def get_jaxpr(fn, *example_args, **kwargs):
    """ClosedJaxpr of any jax-traceable callable (the tier below
    StableHLO; reference analog: the pir program before lowering)."""
    return jax.make_jaxpr(fn, **kwargs)(*example_args)


def get_stablehlo(fn, *example_args) -> str:
    """StableHLO text of the lowered computation — the serialized,
    versioned IR (what paddle_tpu.jit.save persists)."""
    return jax.jit(fn).lower(*example_args).as_text()


def core_uses_pir() -> bool:
    """Reference paddle.base.framework.in_pir_mode analog: the jaxpr/
    StableHLO pipeline is always on."""
    return True
