"""nn.Layer — the module system.

Reference analog: python/paddle/nn/layer/layers.py (Layer: parameter
management, sublayers, hooks, state_dict). Pure-Python object tree holding
Parameters whose values are jax.Arrays; paddle_tpu.jit swaps those values for
tracers to compile whole models, and paddle_tpu.parallel reads
Parameter.sharding_spec to build pjit shardings.
"""
from __future__ import annotations

import copy
from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor
from .parameter import Parameter
from .param_attr import ParamAttr
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype) if dtype else None
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction ----------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype else (
            self._dtype or dtypes.get_default_dtype())
        if default_initializer is None:
            default_initializer = I.Constant(0.0) if is_bias else I.XavierUniform()
        init = I._to_initializer(attr.initializer, default_initializer)
        value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name or "")
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
        t = Tensor(np.zeros((), dtype), stop_gradient=True, name=name or "")
        return t

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return self.create_variable(name, persistable, dtype)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute plumbing ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            layers.pop(name, None) if layers else None
            buffers.pop(name, None) if buffers else None
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            layers[name] = value
            params.pop(name, None) if params else None
        elif isinstance(value, Tensor) and buffers is not None and (
                name in buffers):
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
        if name in self.__dict__:
            object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- traversal -------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in memo:
                    continue
                memo.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=True,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def named_buffers(self, prefix="", include_sublayers=True):
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in memo:
                    continue
                memo.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes -----------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks -----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- state -----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix):
            bname = name.rsplit(".", 1)[-1]
            # walk to owning layer to check persistability
            if bname in self._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                t.set_value(arr.astype(t.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype/device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(dtype)
            for b in self.buffers():
                if dtypes.is_floating_point(b.dtype):
                    b._value = b._value.astype(dtype)
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self._sub_layers.items():
            child_repr = repr(child).split("\n")
            child_repr = [child_repr[0]] + ["  " + l for l in child_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(child_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
