"""Weight initializers.

Reference analog: python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign, Orthogonal, Dirac). Initialization happens host-side with the global
counter-based PRNG, then lands on device once — no init graphs needed.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.random import next_key


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(next_key(), shape, jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        lo = (self.a - 0.0)
        hi = (self.b - 0.0)
        x = jax.random.truncated_normal(next_key(), lo, hi, shape, jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(next_key(), shape, jnp.float32)
                * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return (jax.random.normal(next_key(), shape, jnp.float32)
                * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from ..framework.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        return jnp.reshape(arr, shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(next_key(), (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                out[(g * per + i, i) + mid] = 1.0
        return jnp.asarray(out, dtype=dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def _to_initializer(spec, default=None):
    """Resolve ParamAttr-style initializer specs."""
    if spec is None:
        return default
    if isinstance(spec, Initializer):
        return spec
    if isinstance(spec, (int, float)):
        return Constant(float(spec))
    raise TypeError(f"cannot interpret initializer {spec!r}")
