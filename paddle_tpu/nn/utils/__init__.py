"""nn.utils (reference: python/paddle/nn/utils/): weight_norm, spectral_norm,
parameter vector helpers."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ..parameter import Parameter


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        chunk = vec.numpy()[offset:offset + n].reshape(p.shape)
        p.set_value(chunk)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (recomputed each forward via a
    pre-hook — the reference hooks the same way)."""
    weight = getattr(layer, name)
    w = weight.numpy()
    axes = tuple(i for i in range(w.ndim) if i != dim)
    g = np.sqrt((w ** 2).sum(axis=axes, keepdims=True))
    v = w / np.maximum(g, 1e-12)
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g)))
    layer.add_parameter(name + "_v", Parameter(jnp.asarray(v)))
    del layer._parameters[name]

    def _pre_hook(lyr, inputs):
        from ...ops import math as M
        from ...ops import linalg as L
        gp = lyr._parameters[name + "_g"]
        vp = lyr._parameters[name + "_v"]
        axes_t = [i for i in range(vp.ndim) if i != dim]
        norm = M.sqrt(M.sum(M.square(vp), axis=axes_t, keepdim=True))
        w_t = M.multiply(gp, M.divide(vp, norm))
        object.__setattr__(lyr, name, w_t)
        return None

    layer.register_forward_pre_hook(_pre_hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    w = g.numpy() * v.numpy() / np.sqrt(
        (v.numpy() ** 2).sum(axis=tuple(
            i for i in range(v.ndim) if i != 0), keepdims=True))
    layer.add_parameter(name, Parameter(jnp.asarray(w)))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    weight = getattr(layer, name)
    if dim is None:
        dim = 0
    w = weight.numpy()
    h = w.shape[dim]
    w_mat = np.moveaxis(w, dim, 0).reshape(h, -1)
    u = np.random.randn(h).astype(np.float32)
    u /= np.linalg.norm(u) + eps

    def _pre_hook(lyr, inputs):
        nonlocal u
        wp = lyr._parameters[name + "_orig"]
        wn = wp.numpy()
        wm = np.moveaxis(wn, dim, 0).reshape(h, -1)
        uu = u
        for _ in range(n_power_iterations):
            v = wm.T @ uu
            v /= np.linalg.norm(v) + eps
            uu = wm @ v
            uu /= np.linalg.norm(uu) + eps
        u = uu
        sigma = float(uu @ wm @ v)
        from ...ops import math as M
        w_t = M.divide(wp, float(sigma))
        object.__setattr__(lyr, name, w_t)
        return None

    layer.add_parameter(name + "_orig", Parameter(weight._value))
    del layer._parameters[name]
    layer.register_forward_pre_hook(_pre_hook)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """reference nn/utils/clip_grad_norm_.py — in-place gradient clip by
    total norm across the parameter list; returns the pre-clip norm."""
    import math

    import numpy as np
    import jax.numpy as jnp

    from ...framework.tensor import Tensor

    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    norm_type = float(norm_type)
    if math.isinf(norm_type):
        total = jnp.max(jnp.asarray(
            [jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._value), norm_type))
                for g in grads), 1.0 / norm_type)
    if error_if_nonfinite and not bool(np.isfinite(np.asarray(total))):
        raise RuntimeError(
            f"The total norm of {norm_type} order of the gradients is "
            "non-finite, so it cannot be clipped")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._value = (g._value * scale).astype(g._value.dtype)
    return Tensor(total)
