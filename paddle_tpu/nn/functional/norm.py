"""Normalization functionals.

Reference analog: python/paddle/nn/functional/norm.py → phi layer_norm /
batch_norm kernels. layer_norm accumulates statistics in float32 even under
bf16 inputs (the TPU-correct recipe); XLA fuses the whole normalization into
neighbouring ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor


@defop("layer_norm_op")
def _layer_norm(x, weight, bias, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    begin = x.ndim - len(tuple(normalized_shape))
    return _layer_norm(x, weight, bias, float(epsilon), int(begin))


@defop("rms_norm_op")
def _rms_norm(x, weight, epsilon):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (not in the reference snapshot; standard for modern LLMs)."""
    return _rms_norm(x, weight, float(epsilon))


@defop("batch_norm_train", n_outputs=3)
def _batch_norm_train(x, mean, var, weight, bias, momentum, epsilon,
                      data_format):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    xf = x.astype(jnp.float32)
    batch_mean = jnp.mean(xf, axis=axes)
    batch_var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(batch_mean)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (xf - batch_mean.reshape(shape)) * jax.lax.rsqrt(
        batch_var.reshape(shape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    new_mean = momentum * mean + (1.0 - momentum) * batch_mean
    new_var = momentum * var + (1.0 - momentum) * batch_var
    return out, new_mean, new_var


@defop("batch_norm_eval")
def _batch_norm_eval(x, mean, var, weight, bias, epsilon, data_format):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (x.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    if use_global_stats:
        training = False
    if training:
        out, new_mean, new_var = _batch_norm_train(
            x, running_mean, running_var, weight, bias, float(momentum),
            float(epsilon), data_format)
        # reference semantics: running stats updated in place during training
        if isinstance(running_mean, Tensor):
            running_mean._value = new_mean._value.astype(running_mean.dtype)
        if isinstance(running_var, Tensor):
            running_var._value = new_var._value.astype(running_var.dtype)
        return out
    return _batch_norm_eval(x, running_mean, running_var, weight, bias,
                            float(epsilon), data_format)


@defop("group_norm_op")
def _group_norm(x, weight, bias, num_groups, epsilon, data_format):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    if ch_axis != 1:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xf = x.astype(jnp.float32).reshape(n, num_groups, c // num_groups, -1)
    mean = jnp.mean(xf, axis=(2, 3), keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=(2, 3), keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).reshape(
        n, c, *spatial).astype(x.dtype)
    shape = [1, c] + [1] * len(spatial)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if ch_axis != 1:
        out = jnp.moveaxis(out, 1, -1)
    return out


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    return _group_norm(x, weight, bias, int(num_groups), float(epsilon),
                       data_format)


@defop("instance_norm_op")
def _instance_norm(x, weight, bias, epsilon):
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-05, data_format="NCHW", name=None):
    return _instance_norm(x, weight, bias, float(eps))


@defop("local_response_norm_op")
def _local_response_norm(x, size, alpha, beta, k):
    sq = jnp.square(x)
    c = x.shape[1]
    half = size // 2
    padded = jnp.pad(sq, ((0, 0), (half, size - 1 - half)) +
                     ((0, 0),) * (x.ndim - 2))
    acc = sum(padded[:, i:i + c] for i in range(size))
    return x / (k + alpha * acc) ** beta


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _local_response_norm(x, int(size), float(alpha), float(beta),
                                float(k))
