"""Activation functionals (reference: python/paddle/nn/functional/activation.py
→ phi activation kernels). Single jax fns — XLA fuses them into surrounding
matmuls, which is exactly what the reference's fused-op zoo hand-builds.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor


def _unary(name, jfn):
    @defop(name)
    def op(x):
        return jfn(x)

    def public(x, name=None):
        return op(x)
    public.__name__ = name
    return public


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh_act", jnp.tanh)
silu = _unary("silu", jax.nn.silu)
mish = _unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
softsign = _unary("softsign", jax.nn.soft_sign)
tanhshrink = _unary("tanhshrink", lambda x: x - jnp.tanh(x))
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)


@defop("gelu")
def _gelu(x, approximate):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, bool(approximate))


@defop("leaky_relu")
def _leaky_relu(x, negative_slope):
    return jax.nn.leaky_relu(x, negative_slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, float(negative_slope))


@defop("elu")
def _elu(x, alpha):
    return jax.nn.elu(x, alpha)


def elu(x, alpha=1.0, name=None):
    return _elu(x, float(alpha))


@defop("celu")
def _celu(x, alpha):
    return jax.nn.celu(x, alpha)


def celu(x, alpha=1.0, name=None):
    return _celu(x, float(alpha))


@defop("selu")
def _selu(x, scale, alpha):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu(x, float(scale), float(alpha))


@defop("prelu_op")
def _prelu(x, weight, data_format):
    if weight.ndim == 1 and weight.shape[0] > 1:
        ch_axis = 1 if data_format in ("NCHW", "NCL", "NCDHW") else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = weight.shape[0]
        weight = weight.reshape(shape)
    return jnp.where(x > 0, x, weight * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu(x, weight, data_format)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    from ...framework.random import next_key

    @defop("rrelu")
    def _rrelu(x, key, lower, upper, training):
        if training:
            a = jax.random.uniform(key, x.shape, jnp.float32, lower,
                                   upper).astype(x.dtype)
        else:
            a = jnp.asarray((lower + upper) / 2.0, x.dtype)
        return jnp.where(x >= 0, x, a * x)
    return _rrelu(x, next_key(), float(lower), float(upper), bool(training))


@defop("hardshrink")
def _hardshrink(x, threshold):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0).astype(x.dtype)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, float(threshold))


@defop("softshrink")
def _softshrink(x, threshold):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0)).astype(x.dtype)


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, float(threshold))


@defop("hardtanh")
def _hardtanh(x, mn, mx):
    return jnp.clip(x, mn, mx)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return _hardtanh(x, float(min), float(max))


@defop("hardsigmoid")
def _hardsigmoid(x, slope, offset):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _hardsigmoid(x, float(slope), float(offset))


@defop("hardswish")
def _hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def hardswish(x, name=None):
    return _hardswish(x)


@defop("swish")
def _swish(x):
    return jax.nn.silu(x)


def swish(x, name=None):
    return _swish(x)


@defop("softplus")
def _softplus(x, beta, threshold):
    return jnp.where(x * beta > threshold, x,
                     (1.0 / beta) * jnp.log1p(jnp.exp(beta * x))).astype(x.dtype)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus(x, float(beta), float(threshold))


@defop("thresholded_relu")
def _thresholded_relu(x, threshold, value):
    return jnp.where(x > threshold, x, value).astype(x.dtype)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _thresholded_relu(x, float(threshold), float(value))


@defop("softmax")
def _softmax(x, axis, dtype):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtype as dtypes
    return _softmax(x, int(axis),
                    None if dtype is None else dtypes.convert_dtype(dtype))


@defop("log_softmax")
def _log_softmax(x, axis, dtype):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtype as dtypes
    return _log_softmax(x, int(axis),
                        None if dtype is None else dtypes.convert_dtype(dtype))


@defop("gumbel_softmax")
def _gumbel_softmax(x, key, temperature, hard, axis):
    g = jax.random.gumbel(key, x.shape).astype(x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
        y = onehot + y - jax.lax.stop_gradient(y)
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key
    return _gumbel_softmax(x, next_key(), float(temperature), bool(hard),
                           int(axis))


@defop("maxout_op")
def _maxout(x, groups, axis):
    c = x.shape[axis]
    new = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout(x, int(groups), int(axis))


@defop("glu_op")
def _glu(x, axis):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return _glu(x, int(axis))


@defop("softmax_with_temp")
def _temperature_scaled_softmax(x, t, axis):
    return jax.nn.softmax(x / t, axis=axis)
