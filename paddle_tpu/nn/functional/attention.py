"""Attention functionals.

Reference analog: python/paddle/nn/functional/flash_attention.py:125 and the
fused_attention CUDA ops (/root/reference/paddle/fluid/operators/fused/
fused_attention_op.cu). TPU-native: one fused jax op body that XLA maps onto
the MXU; the Pallas flash-attention kernel (paddle_tpu.kernels) plugs in
underneath `flash_attention` for long sequences.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor
from ...framework.random import next_key


@defop("sdpa_op")
def _sdpa(q, k, v, mask, key, dropout_p, causal, training, scale):
    # q,k,v: [B, S, H, D] (paddle flash-attn layout)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), bool))
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    return _sdpa(query, key, value, attn_mask, next_key(), float(dropout_p),
                 bool(is_causal), bool(training), None)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention analog.

    Dispatches to the Pallas TPU kernel for the no-dropout fast path
    (paddle_tpu/kernels/flash_attention.py); falls back to the fused XLA
    body otherwise.
    """
    from ...kernels import flash_attention as fa_kernel
    if fa_kernel.available() and dropout == 0.0 and not return_softmax:
        out = fa_kernel.flash_attention(query, key, value, causal=causal)
        if return_softmax:
            return out, None
        return out, None
    out = _sdpa(query, key, value, None, next_key(), float(dropout),
                bool(causal), bool(training), None)
    return out, None


@defop("flash_attn_unpadded_op")
def _flash_attn_unpadded(q, k, v, cu_q, cu_k, key, scale, dropout_p,
                         causal, training, want_softmax):
    # packed varlen: q/k/v [total, H, D]; cu_* [B+1] cumulative lengths.
    # TPU-native form: segment ids from searchsorted give a static-shape
    # block-diagonal mask — the data-dependent raggedness lives in the
    # mask VALUES, not the shapes, so one compiled graph serves every
    # packing (XLA requires static shapes; a CUDA varlen kernel indexes
    # ragged rows instead).
    total_q, total_k = q.shape[0], k.shape[0]
    cu_q = cu_q.astype(jnp.int32)
    cu_k = cu_k.astype(jnp.int32)
    seg_q = jnp.searchsorted(cu_q, jnp.arange(total_q), side="right") - 1
    seg_k = jnp.searchsorted(cu_k, jnp.arange(total_k), side="right") - 1
    pos_q = jnp.arange(total_q) - cu_q[seg_q]
    pos_k = jnp.arange(total_k) - cu_k[seg_k]
    valid = seg_q[:, None] == seg_k[None, :]
    if causal:
        valid = jnp.logical_and(valid, pos_q[:, None] >= pos_k[None, :])
    scores = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores = jnp.where(valid[None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows whose segment has zero kv tokens: all-masked → force 0
    probs = jnp.where(valid[None], probs, 0.0).astype(q.dtype)
    if dropout_p > 0.0 and training:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(q.dtype)
    out = jnp.einsum("hqk,khd->qhd", probs, v.astype(probs.dtype))
    out = out.astype(q.dtype)
    # want_softmax is a static (literal-baked) arg: the O(H*total^2)
    # probs buffer is only a compiled output when asked for — returned
    # op outputs can't be DCE'd by XLA
    return (out, probs) if want_softmax else out


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed, unpadded) attention: query/key/value
    [total_seq_len, num_heads, head_dim] with cu_seqlens_* [batch+1]
    boundaries; returns the packed [total_seq_len, num_heads, head_dim]
    output (reference flash_attention.py:269). Sequences attend only
    within their own segment."""
    args = (query, key, value, cu_seqlens_q, cu_seqlens_k, next_key(),
            float(scale), float(dropout), bool(causal), bool(training))
    if return_softmax:
        return _flash_attn_unpadded(*args, True)
    return _flash_attn_unpadded(*args, False), None


@defop("memory_efficient_attention_op")
def _mea(q, k, v, bias, scale, causal):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = (jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale).astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        scores = jnp.where(jnp.tril(jnp.ones((s, t), bool)), scores, -jnp.inf)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """reference: python/paddle/incubate/nn/memory_efficient_attention.py"""
    return _mea(query, key, value, attn_bias,
                None if scale is None else float(scale), False)


@defop("sparse_attention_op")
def _sparse_attention(q, k, v, offset, columns, kp_mask, attn_mask):
    # q/k/v [B, H, S, D]; offset [B, H, S+1] CSR row starts; columns
    # [B, H, nnz] allowed column ids. TPU-native: the CSR layout
    # scatters into a static [S, S] boolean mask per (b, h) — ragged
    # row lengths live in mask VALUES, keeping shapes static for XLA —
    # then one masked-softmax attention body runs on the MXU.
    B, H, S, D = q.shape
    nnz = columns.shape[-1]
    offset = offset.astype(jnp.int32).reshape(B * H, S + 1)
    columns = columns.astype(jnp.int32).reshape(B * H, nnz)

    def one_mask(off, cols):
        row = jnp.searchsorted(off, jnp.arange(nnz), side="right") - 1
        live = jnp.arange(nnz) < off[-1]       # entries past nnz tail
        row = jnp.clip(row, 0, S - 1)
        m = jnp.zeros((S, S), bool)
        return m.at[row, cols].max(live)

    mask = jax.vmap(one_mask)(offset, columns).reshape(B, H, S, S)
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if kp_mask is not None:
        # [B, S] key-padding mask, 0 = masked (reference contract)
        mask = jnp.logical_and(mask,
                               (kp_mask != 0)[:, None, None, :])
    if attn_mask is not None:
        # [S, S], 0 = masked
        mask = jnp.logical_and(mask, (attn_mask != 0)[None, None])
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(mask, probs, 0.0)        # all-masked rows → 0
    return jnp.einsum("bhst,bhtd->bhsd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """CSR block-sparse attention (reference
    python/paddle/nn/functional/sparse_attention.py:19): each query row
    attends only to its CSR row's columns."""
    return _sparse_attention(query, key, value, sparse_csr_offset,
                             sparse_csr_columns, key_padding_mask,
                             attn_mask)
