"""Attention functionals.

Reference analog: python/paddle/nn/functional/flash_attention.py:125 and the
fused_attention CUDA ops (/root/reference/paddle/fluid/operators/fused/
fused_attention_op.cu). TPU-native: one fused jax op body that XLA maps onto
the MXU; the Pallas flash-attention kernel (paddle_tpu.kernels) plugs in
underneath `flash_attention` for long sequences.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor
from ...framework.random import next_key


@defop("sdpa_op")
def _sdpa(q, k, v, mask, key, dropout_p, causal, training, scale):
    # q,k,v: [B, S, H, D] (paddle flash-attn layout)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), bool))
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    return _sdpa(query, key, value, attn_mask, next_key(), float(dropout_p),
                 bool(is_causal), bool(training), None)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention analog.

    Dispatches to the Pallas TPU kernel for the no-dropout fast path
    (paddle_tpu/kernels/flash_attention.py); falls back to the fused XLA
    body otherwise.
    """
    from ...kernels import flash_attention as fa_kernel
    if fa_kernel.available() and dropout == 0.0 and not return_softmax:
        out = fa_kernel.flash_attention(query, key, value, causal=causal)
        if return_softmax:
            return out, None
        return out, None
    out = _sdpa(query, key, value, None, next_key(), float(dropout),
                bool(causal), bool(training), None)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    # varlen packing: fall back to dense with mask built from cu_seqlens
    raise NotImplementedError(
        "varlen flash attention: pack ragged batches densely; TPU path "
        "requires static shapes")


@defop("memory_efficient_attention_op")
def _mea(q, k, v, bias, scale, causal):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = (jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale).astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        scores = jnp.where(jnp.tril(jnp.ones((s, t), bool)), scores, -jnp.inf)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """reference: python/paddle/incubate/nn/memory_efficient_attention.py"""
    return _mea(query, key, value, attn_bias,
                None if scale is None else float(scale), False)


@defop("sparse_attention_op")
def _sparse_attention(q, k, v, offset, columns):
    raise NotImplementedError


def sparse_attention(*args, **kwargs):
    raise NotImplementedError(
        "block-sparse attention: use flash_attention with causal masking; "
        "a Pallas block-sparse kernel is on the roadmap")
