"""Attention functionals.

Reference analog: python/paddle/nn/functional/flash_attention.py:125 and the
fused_attention CUDA ops (/root/reference/paddle/fluid/operators/fused/
fused_attention_op.cu). TPU-native: one fused jax op body that XLA maps onto
the MXU; the Pallas flash-attention kernel (paddle_tpu.kernels) plugs in
underneath `flash_attention` for long sequences.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor
from ...framework.random import next_key


@defop("sdpa_op")
def _sdpa(q, k, v, mask, key, dropout_p, causal, training, scale):
    # q,k,v: [B, S, H, D] (paddle flash-attn layout)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), bool))
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    return _sdpa(query, key, value, attn_mask, next_key(), float(dropout_p),
                 bool(is_causal), bool(training), None)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention analog.

    Dispatches to the Pallas TPU kernel for the no-dropout fast path
    (paddle_tpu/kernels/flash_attention.py); falls back to the fused XLA
    body otherwise.
    """
    from ...kernels import flash_attention as fa_kernel
    if fa_kernel.available() and dropout == 0.0 and not return_softmax:
        out = fa_kernel.flash_attention(query, key, value, causal=causal)
        if return_softmax:
            return out, None
        return out, None
    out = _sdpa(query, key, value, None, next_key(), float(dropout),
                bool(causal), bool(training), None)
    return out, None


# dense varlen is only used when the probs matrix must exist anyway
# (dropout / return_softmax) or the packing is small enough that the
# [H, total_q, total_k] buffer is cheaper than a scan — the threshold is
# on that buffer's ELEMENT count so head count is priced in
_VARLEN_DENSE_MAX = 16 * 1024 * 1024   # H * total_q * total_k
_VARLEN_BLOCK_KV = 512


def _varlen_impl(n_elements: int) -> str:
    """'blockwise' | 'dense' for a packing whose probs buffer would hold
    n_elements (= H * total_q * total_k). Precedence mirrors the
    attention selector: env override (PADDLE_TPU_VARLEN_IMPL, the
    operator's absolute escape hatch), then the evidence-gated kernel
    registry's winner for this backend class, then the element-count
    heuristic. A registry 'dense' winner is a PREFERENCE, not a license
    to OOM: it only applies while the probs buffer stays under the
    memory guard — a wildcard row measured on a small packing must not
    force an O(n_elements) materialization at every size."""
    import os
    impl = os.environ.get("PADDLE_TPU_VARLEN_IMPL", "")
    if impl in ("blockwise", "dense"):
        return impl
    from ...kernels import registry
    impl = registry.winner("varlen_attention",
                           backend=registry.backend_class()) or ""
    if impl == "dense" and n_elements > _VARLEN_DENSE_MAX:
        impl = "blockwise"
    if impl not in ("blockwise", "dense"):
        impl = "blockwise" if n_elements > _VARLEN_DENSE_MAX else "dense"
    return impl


def _varlen_segments(cu, total):
    """Segment id and within-segment position for each packed row."""
    cu = cu.astype(jnp.int32)
    seg = jnp.searchsorted(cu, jnp.arange(total), side="right") - 1
    pos = jnp.arange(total) - cu[seg]
    return seg, pos


def _varlen_blockwise(q, k, v, seg_q, pos_q, seg_k, pos_k, scale, causal):
    """Online-softmax over KV blocks for the packed form: memory is
    O(H * total_q * block) instead of the dense O(H * total_q * total_k)
    — the varlen analog of kernels.flash_attention._blockwise_attention_lse
    with the block-diagonal segment mask folded into each block."""
    total_q, H, D = q.shape
    total_k = k.shape[0]
    blk = min(_VARLEN_BLOCK_KV, total_k)
    pad = (-total_k) % blk
    if pad:
        k = jnp.concatenate([k, jnp.zeros((pad, H, D), k.dtype)], 0)
        v = jnp.concatenate([v, jnp.zeros((pad, H, D), v.dtype)], 0)
        # padding rows get segment -1: never equal to any real seg_q >= 0
        seg_k = jnp.concatenate(
            [seg_k, jnp.full((pad,), -1, seg_k.dtype)], 0)
        pos_k = jnp.concatenate([pos_k, jnp.zeros((pad,), pos_k.dtype)], 0)
    nblk = (total_k + pad) // blk
    kb = k.reshape(nblk, blk, H, D)
    vb = v.reshape(nblk, blk, H, D)
    sb = seg_k.reshape(nblk, blk)
    pb = pos_k.reshape(nblk, blk)

    def step(carry, inputs):
        m, l, acc = carry
        kblk, vblk, segs, poss = inputs
        scores = jnp.einsum("qhd,khd->hqk", q, kblk,
                            preferred_element_type=jnp.float32) * scale
        valid = seg_q[:, None] == segs[None, :]
        if causal:
            valid = jnp.logical_and(valid,
                                    pos_q[:, None] >= poss[None, :])
        scores = jnp.where(valid[None], scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "hqk,khd->hqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((H, total_q), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((H, total_q), jnp.float32)
    acc0 = jnp.zeros((H, total_q, D), jnp.float32)
    # reverse-mode AD over a plain scan saves every block's residuals
    # (p, scores: O(H·total_q·blk) EACH, × nblk = the dense blowup this
    # path exists to avoid); checkpointing the body stores only the
    # (m, l, acc) carry per block and rebuilds p in the backward — the
    # same recompute trade the flash backward makes
    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(step), (m0, l0, acc0),
                                  (kb, vb, sb, pb))
    # rows whose segment has zero kv tokens stay all-masked: l == 0 → 0
    out = acc / jnp.maximum(l, 1e-37)[..., None]
    return jnp.swapaxes(out, 0, 1).astype(q.dtype)   # [total_q, H, D]


@defop("flash_attn_unpadded_op")
def _flash_attn_unpadded(q, k, v, cu_q, cu_k, key, scale, dropout_p,
                         causal, training, want_softmax):
    # packed varlen: q/k/v [total, H, D]; cu_* [B+1] cumulative lengths.
    # TPU-native form: segment ids from searchsorted give a static-shape
    # block-diagonal mask — the data-dependent raggedness lives in the
    # mask VALUES, not the shapes, so one compiled graph serves every
    # packing (XLA requires static shapes; a CUDA varlen kernel indexes
    # ragged rows instead).
    total_q, total_k = q.shape[0], k.shape[0]
    seg_q, pos_q = _varlen_segments(cu_q, total_q)
    seg_k, pos_k = _varlen_segments(cu_k, total_k)
    dense_needed = want_softmax or (dropout_p > 0.0 and training)
    if (not dense_needed
            and _varlen_impl(q.shape[1] * total_q * total_k)
            == "blockwise"):
        return _varlen_blockwise(q, k, v, seg_q, pos_q, seg_k, pos_k,
                                 scale, causal)
    valid = seg_q[:, None] == seg_k[None, :]
    if causal:
        valid = jnp.logical_and(valid, pos_q[:, None] >= pos_k[None, :])
    scores = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores = jnp.where(valid[None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows whose segment has zero kv tokens: all-masked → force 0
    probs = jnp.where(valid[None], probs, 0.0).astype(q.dtype)
    if dropout_p > 0.0 and training:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(q.dtype)
    out = jnp.einsum("hqk,khd->qhd", probs, v.astype(probs.dtype))
    out = out.astype(q.dtype)
    # want_softmax is a static (literal-baked) arg: the O(H*total^2)
    # probs buffer is only a compiled output when asked for — returned
    # op outputs can't be DCE'd by XLA
    return (out, probs) if want_softmax else out


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed, unpadded) attention: query/key/value
    [total_seq_len, num_heads, head_dim] with cu_seqlens_* [batch+1]
    boundaries; returns the packed [total_seq_len, num_heads, head_dim]
    output (reference flash_attention.py:269). Sequences attend only
    within their own segment.

    Large packings run the blockwise online-softmax path (O(total*block)
    memory, flash-style); the dense O(total^2) scores buffer is built
    only for small inputs or when dropout / return_softmax force the
    full probs matrix to exist."""
    args = (query, key, value, cu_seqlens_q, cu_seqlens_k, next_key(),
            float(scale), float(dropout), bool(causal), bool(training))
    if return_softmax:
        return _flash_attn_unpadded(*args, True)
    return _flash_attn_unpadded(*args, False), None


@defop("memory_efficient_attention_op")
def _mea(q, k, v, bias, scale, causal):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = (jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale).astype(jnp.float32)
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        scores = jnp.where(jnp.tril(jnp.ones((s, t), bool)), scores, -jnp.inf)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """reference: python/paddle/incubate/nn/memory_efficient_attention.py"""
    return _mea(query, key, value, attn_bias,
                None if scale is None else float(scale), False)


@defop("sparse_attention_op")
def _sparse_attention(q, k, v, offset, columns, kp_mask, attn_mask):
    # q/k/v [B, H, S, D]; offset [B, H, S+1] CSR row starts; columns
    # [B, H, nnz] allowed column ids. TPU-native: the CSR layout
    # scatters into a static [S, S] boolean mask per (b, h) — ragged
    # row lengths live in mask VALUES, keeping shapes static for XLA —
    # then one masked-softmax attention body runs on the MXU.
    B, H, S, D = q.shape
    nnz = columns.shape[-1]
    offset = offset.astype(jnp.int32).reshape(B * H, S + 1)
    columns = columns.astype(jnp.int32).reshape(B * H, nnz)

    def one_mask(off, cols):
        row = jnp.searchsorted(off, jnp.arange(nnz), side="right") - 1
        live = jnp.arange(nnz) < off[-1]       # entries past nnz tail
        row = jnp.clip(row, 0, S - 1)
        m = jnp.zeros((S, S), bool)
        return m.at[row, cols].max(live)

    mask = jax.vmap(one_mask)(offset, columns).reshape(B, H, S, S)
    scale = 1.0 / math.sqrt(D)
    # accumulate in the input precision when it exceeds f32 (the
    # reference supports float64); otherwise f32
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    scores = jnp.einsum("bhsd,bhtd->bhst", q.astype(acc_dt),
                        k.astype(acc_dt)) * scale
    if kp_mask is not None:
        # [B, S] key-padding mask, 0 = masked (reference contract)
        mask = jnp.logical_and(mask,
                               (kp_mask != 0)[:, None, None, :])
    if attn_mask is not None:
        # [S, S], 0 = masked
        mask = jnp.logical_and(mask, (attn_mask != 0)[None, None])
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(mask, probs, 0.0)        # all-masked rows → 0
    return jnp.einsum("bhst,bhtd->bhsd", probs,
                      v.astype(acc_dt)).astype(q.dtype)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """CSR block-sparse attention (reference
    python/paddle/nn/functional/sparse_attention.py:19): each query row
    attends only to its CSR row's columns.

    Correct-but-dense fallback: the CSR pattern is scattered into a full
    [B, H, S, S] mask and scores are computed densely, so compute/memory
    are O(S^2) regardless of sparsity — fine for the reference's
    moderate S, not a long-context kernel (use flash/splash paths for
    that)."""
    return _sparse_attention(query, key, value, sparse_csr_offset,
                             sparse_csr_columns, key_padding_mask,
                             attn_mask)
