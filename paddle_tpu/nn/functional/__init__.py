"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional/)."""
from .activation import (  # noqa: F401
    relu, relu6, sigmoid, tanh, silu, mish, softsign, tanhshrink,
    log_sigmoid, gelu, leaky_relu, elu, celu, selu, prelu, rrelu,
    hardshrink, softshrink, hardtanh, hardsigmoid, hardswish, swish,
    softplus, thresholded_relu, softmax, log_softmax, gumbel_softmax,
    maxout, glu)
from .common import (  # noqa: F401
    linear, embedding, dropout, dropout2d, dropout3d, alpha_dropout,
    normalize, label_smooth, pad, cosine_similarity, pixel_shuffle,
    pixel_unshuffle, channel_shuffle, interpolate, upsample, unfold, fold,
    bilinear, sequence_mask, grid_sample)
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose)
from .pooling import (  # noqa: F401
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, lp_pool2d)
from .norm import (  # noqa: F401
    layer_norm, rms_norm, batch_norm, group_norm, instance_norm,
    local_response_norm)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, mse_loss, l1_loss,
    smooth_l1_loss, huber_loss, nll_loss, binary_cross_entropy,
    binary_cross_entropy_with_logits, kl_div, margin_ranking_loss,
    hinge_embedding_loss, cosine_embedding_loss, triplet_margin_loss,
    log_loss, square_error_cost, sigmoid_focal_loss, ctc_loss, npair_loss)
from .attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention, flash_attn_unpadded,
    memory_efficient_attention, sparse_attention)
from ...ops.creation import one_hot  # noqa: F401
from ...ops.manipulation import gather, gather_nd, scatter, scatter_nd  # noqa: F401
from ...ops.math import scale  # noqa: F401
from .extra import (  # noqa: F401
    pairwise_distance, elu_, relu_, softmax_, tanh_, diag_embed,
    zeropad2d, max_unpool1d, max_unpool2d, max_unpool3d,
    adaptive_max_pool3d, dice_loss, hsigmoid_loss,
    multi_label_soft_margin_loss, poisson_nll_loss,
    margin_cross_entropy, rnnt_loss, affine_grid, gather_tree,
    temporal_shift, class_center_sample,
    triplet_margin_with_distance_loss, multi_margin_loss,
    soft_margin_loss, gaussian_nll_loss)
