"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).
Lowered to lax.reduce_window — XLA's native windowed reduction."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import apply, defop
from ...framework.tensor import Tensor


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pool_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return tuple((p, p) for p in padding)
    if len(padding) == 2 * n:
        return tuple((padding[2 * i], padding[2 * i + 1]) for i in range(n))
    return tuple(tuple(p) for p in padding)


def _reduce_window(x, init, op, window, strides, padding, nd, chan_first):
    if chan_first:
        dims = (1, 1) + window
        strd = (1, 1) + strides
        pad = ((0, 0), (0, 0)) + padding if not isinstance(padding, str) else padding
    else:
        dims = (1,) + window + (1,)
        strd = (1,) + strides + (1,)
        pad = ((0, 0),) + padding + ((0, 0),) if not isinstance(padding, str) else padding
    if isinstance(pad, str):
        pad_cfg = jax.lax.padtype_to_pads(x.shape, dims, strd, pad)
    else:
        pad_cfg = pad
    return jax.lax.reduce_window(x, init, op, dims, strd, pad_cfg)


def _max_pool(x, window, strides, padding, ceil_mode, nd, chan_first):
    if ceil_mode and not isinstance(padding, str):
        # extend padding on the high side so the last partial window counts
        spatial = x.shape[2:2 + nd] if chan_first else x.shape[1:1 + nd]
        padding = tuple(
            (p[0], p[1] + _ceil_extra(s, w, st, p))
            for s, w, st, p in zip(spatial, window, strides, padding))
    # -inf init lets jax recognize the differentiable select-and-scatter
    # pattern for reduce_window_max
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    return _reduce_window(x, neg, jax.lax.max, window, strides, padding, nd,
                          chan_first)


def _ceil_extra(size, w, stride, pad):
    padded = size + pad[0] + pad[1]
    import math
    out_floor = (padded - w) // stride + 1
    out_ceil = math.ceil((padded - w) / stride) + 1
    return (out_ceil - out_floor) * stride


def _avg_pool(x, window, strides, padding, ceil_mode, exclusive, nd,
              chan_first):
    if ceil_mode and not isinstance(padding, str):
        spatial = x.shape[2:2 + nd] if chan_first else x.shape[1:1 + nd]
        padding = tuple(
            (p[0], p[1] + _ceil_extra(s, w, st, p))
            for s, w, st, p in zip(spatial, window, strides, padding))
    summed = _reduce_window(x, 0.0, jax.lax.add, window, strides, padding,
                            nd, chan_first)
    if exclusive and (isinstance(padding, str) or
                      any(p != (0, 0) for p in padding)):
        ones = jnp.ones_like(x)
        counts = _reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                padding, nd, chan_first)
        return summed / counts
    return summed / float(np.prod(window))


@defop("max_pool1d_op")
def _max_pool1d(x, k, s, p, ceil_mode):
    return _max_pool(x, k, s, p, ceil_mode, 1, True)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    stride = stride or kernel_size
    if return_mask:
        return _masked_max_pool(x, kernel_size, stride, padding, 1,
                                "NCL", "max_pool1d_mask_op",
                                ceil_mode=ceil_mode)
    return _max_pool1d(x, _tuplize(kernel_size, 1), _tuplize(stride, 1),
                       _pool_padding(padding, 1), bool(ceil_mode))


@defop("max_pool2d_op")
def _max_pool2d(x, k, s, p, ceil_mode, chan_first):
    return _max_pool(x, k, s, p, ceil_mode, 2, chan_first)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    stride = stride or kernel_size
    if return_mask:
        return _masked_max_pool(x, kernel_size, stride, padding, 2,
                                data_format, "max_pool2d_mask_op",
                                ceil_mode=ceil_mode)
    return _max_pool2d(x, _tuplize(kernel_size, 2), _tuplize(stride, 2),
                       _pool_padding(padding, 2), bool(ceil_mode),
                       data_format == "NCHW")


@defop("max_pool3d_op")
def _max_pool3d(x, k, s, p, ceil_mode, chan_first):
    return _max_pool(x, k, s, p, ceil_mode, 3, chan_first)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    stride = stride or kernel_size
    if return_mask:
        return _masked_max_pool(x, kernel_size, stride, padding, 3,
                                data_format, "max_pool3d_mask_op",
                                ceil_mode=ceil_mode)
    return _max_pool3d(x, _tuplize(kernel_size, 3), _tuplize(stride, 3),
                       _pool_padding(padding, 3), bool(ceil_mode),
                       data_format == "NCDHW")


@defop("avg_pool1d_op")
def _avg_pool1d(x, k, s, p, ceil_mode, exclusive):
    return _avg_pool(x, k, s, p, ceil_mode, exclusive, 1, True)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    stride = stride or kernel_size
    return _avg_pool1d(x, _tuplize(kernel_size, 1), _tuplize(stride, 1),
                       _pool_padding(padding, 1), bool(ceil_mode),
                       bool(exclusive))


@defop("avg_pool2d_op")
def _avg_pool2d(x, k, s, p, ceil_mode, exclusive, chan_first):
    return _avg_pool(x, k, s, p, ceil_mode, exclusive, 2, chan_first)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    stride = stride or kernel_size
    return _avg_pool2d(x, _tuplize(kernel_size, 2), _tuplize(stride, 2),
                       _pool_padding(padding, 2), bool(ceil_mode),
                       bool(exclusive), data_format == "NCHW")


@defop("avg_pool3d_op")
def _avg_pool3d(x, k, s, p, ceil_mode, exclusive, chan_first):
    return _avg_pool(x, k, s, p, ceil_mode, exclusive, 3, chan_first)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    stride = stride or kernel_size
    return _avg_pool3d(x, _tuplize(kernel_size, 3), _tuplize(stride, 3),
                       _pool_padding(padding, 3), bool(ceil_mode),
                       bool(exclusive), data_format == "NCDHW")


def _adaptive_window(in_size, out_size):
    # windows per output position; uniform when divisible
    return in_size // out_size, in_size // out_size


@defop("adaptive_avg_pool2d_op")
def _adaptive_avg_pool2d(x, out_hw, chan_first):
    if chan_first:
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    oh, ow = out_hw
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        return _avg_pool(x, (kh, kw), (kh, kw),
                         ((0, 0), (0, 0)), False, False, 2, chan_first)
    # general: mean over index buckets
    axis_h = 2 if chan_first else 1
    splits_h = [x.shape[axis_h] * i // oh for i in range(oh + 1)]
    rows = [jnp.mean(jax.lax.slice_in_dim(x, splits_h[i], splits_h[i + 1],
                                          axis=axis_h), axis=axis_h,
                     keepdims=True) for i in range(oh)]
    x = jnp.concatenate(rows, axis=axis_h)
    axis_w = 3 if chan_first else 2
    splits_w = [x.shape[axis_w] * i // ow for i in range(ow + 1)]
    cols = [jnp.mean(jax.lax.slice_in_dim(x, splits_w[i], splits_w[i + 1],
                                          axis=axis_w), axis=axis_w,
                     keepdims=True) for i in range(ow)]
    return jnp.concatenate(cols, axis=axis_w)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool2d(x, _tuplize(output_size, 2),
                                data_format == "NCHW")


@defop("adaptive_avg_pool1d_op")
def _adaptive_avg_pool1d(x, out):
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return _avg_pool(x, (k,), (k,), ((0, 0),), False, False, 1, True)
    splits = [l * i // out for i in range(out + 1)]
    parts = [jnp.mean(x[:, :, splits[i]:splits[i + 1]], axis=2,
                      keepdims=True) for i in range(out)]
    return jnp.concatenate(parts, axis=2)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_avg_pool1d(x, int(output_size))


@defop("adaptive_avg_pool3d_op")
def _adaptive_avg_pool3d(x, out_dhw, chan_first):
    outs = out_dhw
    for i in range(3):
        axis = (2 + i) if chan_first else (1 + i)
        size = x.shape[axis]
        out = outs[i]
        splits = [size * j // out for j in range(out + 1)]
        parts = [jnp.mean(jax.lax.slice_in_dim(x, splits[j], splits[j + 1],
                                               axis=axis), axis=axis,
                          keepdims=True) for j in range(out)]
        x = jnp.concatenate(parts, axis=axis)
    return x


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_avg_pool3d(x, _tuplize(output_size, 3),
                                data_format == "NCDHW")


@defop("adaptive_max_pool2d_op")
def _adaptive_max_pool2d(x, out_hw):
    h, w = x.shape[2], x.shape[3]
    oh, ow = out_hw
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        return _max_pool(x, (kh, kw), (kh, kw), ((0, 0), (0, 0)), False, 2,
                         True)
    splits_h = [h * i // oh for i in range(oh + 1)]
    rows = [jnp.max(x[:, :, splits_h[i]:splits_h[i + 1], :], axis=2,
                    keepdims=True) for i in range(oh)]
    x = jnp.concatenate(rows, axis=2)
    splits_w = [w * i // ow for i in range(ow + 1)]
    cols = [jnp.max(x[:, :, :, splits_w[i]:splits_w[i + 1]], axis=3,
                    keepdims=True) for i in range(ow)]
    return jnp.concatenate(cols, axis=3)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool2d(x, _tuplize(output_size, 2))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    @defop("adaptive_max_pool1d_op")
    def _amp1(x, out):
        l = x.shape[2]
        splits = [l * i // out for i in range(out + 1)]
        parts = [jnp.max(x[:, :, splits[i]:splits[i + 1]], axis=2,
                         keepdims=True) for i in range(out)]
        return jnp.concatenate(parts, axis=2)
    return _amp1(x, int(output_size))


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    @defop("lp_pool2d_op")
    def _lp(x, p, k, s, pad, chan_first):
        powed = jnp.abs(x) ** p
        summed = _reduce_window(powed, 0.0, jax.lax.add, k, s, pad, 2,
                                chan_first)
        return summed ** (1.0 / p)
    stride = stride or kernel_size
    return _lp(x, float(norm_type), _tuplize(kernel_size, 2),
               _tuplize(stride, 2), _pool_padding(padding, 2),
               data_format == "NCHW")


# ------------------------------------------------------------------
# max-pool argmax masks (reference return_mask=True: indices flattened
# over the spatial dims per (N, C) — the contract max_unpool consumes).
# Static kernel-offset stacking: for each of the prod(k) offsets, a
# strided slice of the (-inf padded) input aligns all windows; argmax
# over the offset axis picks the winner, and the winning offset maps
# back to flat input coordinates. Fully static shapes, no dynamic
# gather.
# ------------------------------------------------------------------
def _max_pool_with_mask(x, ks, st, pd, nd, ceil_mode=False):
    import itertools
    spatial = x.shape[2:]
    if ceil_mode:
        out_sp = tuple(
            -(-(spatial[i] + pd[i][0] + pd[i][1] - ks[i]) // st[i]) + 1
            for i in range(nd))
        extra = tuple(
            max(0, (out_sp[i] - 1) * st[i] + ks[i]
                - (spatial[i] + pd[i][0] + pd[i][1]))
            for i in range(nd))
        pd = tuple((pd[i][0], pd[i][1] + extra[i]) for i in range(nd))
    else:
        out_sp = tuple(
            (spatial[i] + pd[i][0] + pd[i][1] - ks[i]) // st[i] + 1
            for i in range(nd))
    pads = [(0, 0), (0, 0)] + [(p[0], p[1]) for p in pd]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, pads, constant_values=neg)

    slabs, flat_idx = [], []
    for off in itertools.product(*[range(k) for k in ks]):
        sl = [slice(None), slice(None)]
        for i in range(nd):
            stop = off[i] + (out_sp[i] - 1) * st[i] + 1
            sl.append(slice(off[i], stop, st[i]))
        slabs.append(xp[tuple(sl)])
        # flat input index of this offset at every output position
        coords = []
        for i in range(nd):
            c = (jnp.arange(out_sp[i]) * st[i] + off[i] - pd[i][0])
            coords.append(c)
        mesh = jnp.meshgrid(*coords, indexing="ij")
        flat = jnp.zeros(out_sp, jnp.int32)
        for i in range(nd):
            flat = flat * spatial[i] + jnp.clip(mesh[i], 0,
                                                spatial[i] - 1)
        flat_idx.append(flat)
    stack = jnp.stack(slabs)                      # [K, N, C, *out]
    idx_stack = jnp.stack(flat_idx)               # [K, *out]
    win = jnp.argmax(stack, axis=0)               # [N, C, *out]
    out = jnp.max(stack, axis=0)
    P = int(np.prod(out_sp))
    idx_flat = idx_stack.reshape(idx_stack.shape[0], P)   # [K, P]
    win_flat = win.reshape(win.shape[0], win.shape[1], P)
    mask = idx_flat[win_flat, jnp.arange(P)[None, None, :]]
    mask = mask.reshape(win.shape)
    return out, mask


def _masked_max_pool(x, kernel_size, stride, padding, nd, data_format,
                     op_name, ceil_mode=False):
    expected = {1: "NCL", 2: "NCHW", 3: "NCDHW"}[nd]
    if data_format != expected:
        raise NotImplementedError(
            f"return_mask=True supports {expected} only")
    return apply(
        op_name,
        lambda xv, ks=None, st=None, pd=None, nd_=None, cm=False:
            _max_pool_with_mask(xv, ks, st, pd, nd_, ceil_mode=cm),
        x, _nondiff_outputs=(1,), ks=_tuplize(kernel_size, nd),
        st=_tuplize(stride, nd), pd=_pool_padding(padding, nd), nd_=nd,
        cm=bool(ceil_mode))
