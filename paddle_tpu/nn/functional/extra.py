"""nn.functional long-tail parity (reference
python/paddle/nn/functional/__init__.py names missing from the v1
surface): distance/pad/diag helpers, the loss zoo
(dice/hsigmoid/poisson-nll/margin-CE/rnnt/triplet-distance/multi-margin/
soft-margin/gaussian-nll/multi-label), vision warps
(affine_grid/temporal_shift), beam-search gather_tree,
class_center_sample, inplace activation variants, and the max-unpool
family."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import apply, defop
from ...framework.tensor import Tensor, inplace_rebind

__all__ = [
    "pairwise_distance", "elu_", "relu_", "softmax_", "tanh_",
    "diag_embed", "zeropad2d", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "adaptive_max_pool3d", "dice_loss", "hsigmoid_loss",
    "multi_label_soft_margin_loss", "poisson_nll_loss",
    "margin_cross_entropy", "rnnt_loss", "affine_grid", "gather_tree",
    "temporal_shift", "class_center_sample",
    "triplet_margin_with_distance_loss", "multi_margin_loss",
    "soft_margin_loss", "gaussian_nll_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "none":
        return loss
    raise ValueError(
        f"reduction should be 'mean', 'sum' or 'none', got {reduction}")


# ------------------------------------------------------------ distances
@defop("pairwise_distance_op")
def _pairwise_distance(x, y, *, p, epsilon, keepdim):
    d = x - y + epsilon
    if math.isinf(p):
        return jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
    s = jnp.sum(jnp.power(jnp.abs(d), p), axis=-1, keepdims=keepdim)
    pos = s > 0
    return jnp.where(pos, jnp.power(jnp.where(pos, s, 1.0), 1.0 / p),
                     0.0)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False,
                      name=None):
    """reference nn/functional/distance.py pairwise_distance —
    ||x - y + eps||_p along the last dim."""
    return _pairwise_distance(x, y, p=float(p), epsilon=float(epsilon),
                              keepdim=bool(keepdim))


# ---------------------------------------------------- inplace activations
def relu_(x, name=None):
    from .activation import relu
    return inplace_rebind(x, relu(x))


def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    return inplace_rebind(x, elu(x, alpha))


def tanh_(x, name=None):
    from ...ops.math import tanh
    return inplace_rebind(x, tanh(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    from .activation import softmax
    return inplace_rebind(x, softmax(x, axis=axis, dtype=dtype))


# ------------------------------------------------------------- reshape/pad
@defop("diag_embed_op")
def _diag_embed(x, *, offset, dim1, dim2):
    n = x.shape[-1] + abs(offset)
    nd = x.ndim + 1
    d1, d2 = dim1 % nd, dim2 % nd
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = base.at[..., r, c].set(x)
    # move the two new trailing dims to (dim1, dim2)
    perm = list(range(x.ndim - 1))
    pos = {d1: x.ndim - 1, d2: x.ndim}
    full = []
    src = iter(perm)
    for i in range(nd):
        full.append(pos[i] if i in pos else next(src))
    return jnp.transpose(out, full)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """reference tensor/creation.py diag_embed: last-dim vectors become
    diagonals of new (dim1, dim2) planes."""
    return _diag_embed(input, offset=int(offset), dim1=int(dim1),
                       dim2=int(dim2))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """reference nn/functional/common.py zeropad2d — [l, r, t, b]."""
    from .common import pad
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


# ---------------------------------------------------------------- losses
@defop("dice_loss_op")
def _dice_loss(input, label, *, epsilon):
    lab = jax.nn.one_hot(label[..., 0], input.shape[-1],
                         dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * lab, axis=reduce_dims)
    union = jnp.sum(input, reduce_dims) + jnp.sum(lab, reduce_dims)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference nn/functional/loss.py:35."""
    return _dice_loss(input, label, epsilon=float(epsilon))


@defop("soft_margin_loss_op")
def _soft_margin_loss(input, label):
    return jnp.log1p(jnp.exp(-label.astype(input.dtype) * input))


def soft_margin_loss(input, label, reduction="mean", name=None):
    """reference loss.py soft_margin_loss: log(1+exp(-y*x)),
    y in {-1, 1}."""
    return apply("soft_margin_reduced",
                 lambda i, l, red=None: _reduce(
                     _soft_margin_loss._raw_fn(i, l), red),
                 input, label, red=reduction)


@defop("poisson_nll_loss_op")
def _poisson_nll_loss(input, label, *, log_input, full, epsilon):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling approximation for label! (only where label > 1)
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * jnp.pi * label))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return loss


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """reference loss.py poisson_nll_loss."""
    if epsilon <= 0:
        raise ValueError(
            f"The value of `epsilon` in PoissonNLLLoss should be "
            f"positive, but received {epsilon}")
    out = _poisson_nll_loss(input, label, log_input=bool(log_input),
                            full=bool(full), epsilon=float(epsilon))
    return apply("reduce_loss", lambda v, red=None: _reduce(v, red),
                 out, red=reduction)


@defop("multi_label_soft_margin_op")
def _ml_soft_margin(input, label, weight):
    # loss = -mean_c [ y log sigmoid(x) + (1-y) log sigmoid(-x) ]
    term = (label * jax.nn.log_sigmoid(input)
            + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        term = term * weight
    return -jnp.mean(term, axis=-1)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """reference loss.py multi_label_soft_margin_loss."""
    return apply("ml_soft_margin_reduced",
                 lambda i, l, w, red=None: _reduce(
                     _ml_soft_margin._raw_fn(i, l, w), red),
                 input, label, weight, red=reduction)


@defop("multi_margin_loss_op")
def _multi_margin(input, label, weight, *, p, margin):
    N, C = input.shape
    tgt = input[jnp.arange(N), label]
    diff = jnp.maximum(margin - tgt[:, None] + input, 0.0)
    diff = jnp.power(diff, p)
    if weight is not None:
        diff = diff * weight[label][:, None]
    mask = jax.nn.one_hot(label, C, dtype=input.dtype)
    return jnp.sum(diff * (1 - mask), axis=1) / C


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference loss.py multi_margin_loss."""
    return apply("multi_margin_reduced",
                 lambda i, l, w, red=None, pp=1, mg=1.0: _reduce(
                     _multi_margin._raw_fn(i, l, w, p=pp, margin=mg),
                     red),
                 input, label, weight, red=reduction, pp=int(p),
                 mg=float(margin))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None,
                                      margin=1.0, swap=False,
                                      reduction="mean", name=None):
    """reference loss.py triplet_margin_with_distance_loss."""
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        from ...ops.math import minimum
        dn = minimum(dn, dist(positive, negative))
    return apply(
        "triplet_dist_reduced",
        lambda a, b, red=None, mg=1.0: _reduce(
            jnp.maximum(a - b + mg, 0.0), red),
        dp, dn, red=reduction, mg=float(margin))


@defop("gaussian_nll_loss_op")
def _gaussian_nll(input, label, variance, *, full, epsilon):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi, input.dtype))
    return loss


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """reference loss.py gaussian_nll_loss."""
    out = _gaussian_nll(input, label, variance, full=bool(full),
                        epsilon=float(epsilon))
    return apply("reduce_loss", lambda v, red=None: _reduce(v, red),
                 out, red=reduction)


@defop("hsigmoid_loss_op")
def _hsigmoid_loss(x, label, weight, bias, path_table, path_code,
                   *, num_classes):
    """Hierarchical sigmoid (reference phi SimpleCode tree when
    path_table is None: code(c) = c + num_classes, node index at bit j =
    (code >> (j+1)) - 1, bit j = (code >> j) & 1, path length =
    floor(log2(code)))."""
    N = x.shape[0]
    if path_table is None:
        code = label + num_classes
        # max path length over the tree; per-sample mask trims the rest
        L = int(math.floor(math.log2(2 * num_classes - 1)))
        js = jnp.arange(L)
        idxs = (code[:, None] >> (js[None, :] + 1)) - 1     # [N, L]
        bits = (code[:, None] >> js[None, :]) & 1
        lengths = jnp.floor(
            jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
        valid = js[None, :] < lengths[:, None]
    else:
        idxs = path_table
        bits = path_code
        valid = idxs >= 0
        idxs = jnp.maximum(idxs, 0)
    w = weight[idxs]                                  # [N, L, D]
    z = jnp.einsum("nld,nd->nl", w, x)
    if bias is not None:
        z = z + bias[idxs][..., 0] if bias.ndim == 2 else z + bias[idxs]
    t = bits.astype(x.dtype)
    bce = jax.nn.softplus(z) - t * z
    return jnp.sum(jnp.where(valid, bce, 0.0), axis=1, keepdims=True)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference nn/functional/loss.py hsigmoid_loss — [N, 1] per-sample
    loss."""
    return _hsigmoid_loss(input, label, weight, bias, path_table,
                          path_code, num_classes=int(num_classes))


@defop("margin_cross_entropy_op", n_outputs=2, nondiff_outputs=(1,))
def _margin_ce(logits, label, *, margin1, margin2, margin3, scale):
    theta = jnp.arccos(jnp.clip(logits, -1.0, 1.0))
    mod = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(label, logits.shape[-1],
                            dtype=logits.dtype)
    adjusted = jnp.where(onehot > 0, mod, logits) * scale
    lse = jax.scipy.special.logsumexp(adjusted, axis=-1)
    tgt = jnp.sum(adjusted * onehot, axis=-1)
    loss = (lse - tgt)[:, None]
    softmax = jnp.exp(adjusted - lse[:, None])
    return loss, softmax


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """reference loss.py margin_cross_entropy (ArcFace-family margin
    softmax): target cosine -> cos(m1*theta + m2) - m3, scaled by s."""
    loss, softmax = _margin_ce(logits, label, margin1=float(margin1),
                               margin2=float(margin2),
                               margin3=float(margin3),
                               scale=float(scale))
    if reduction is not None:
        loss = apply("reduce_loss", lambda v, red=None: _reduce(v, red),
                     loss, red=reduction)
    if return_softmax:
        return loss, softmax
    return loss


@defop("rnnt_loss_op")
def _rnnt_loss(logits, labels, logit_lengths, label_lengths, *, blank,
               fastemit_lambda):
    """Transducer loss (Graves 2012): alpha DP over the [T, U+1]
    lattice, log domain; lax.scan over t, inner scan over u. FastEmit
    (Yu et al. 2021, the reference's fastemit_lambda) scales the EMIT
    branch's gradient by (1+lambda) — implemented value-preservingly as
    e' = (1+l)*e - stop_gradient(l*e)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    B, T, U1, V = logp.shape
    U = U1 - 1
    neg_inf = jnp.asarray(-1e30, logp.dtype)

    blank_lp = logp[..., blank]                       # [B, T, U+1]
    emit_lp = jnp.take_along_axis(
        logp[:, :, :U, :], labels[:, None, :, None], axis=-1
    )[..., 0]                                         # [B, T, U]
    if fastemit_lambda != 0.0:
        emit_lp = ((1.0 + fastemit_lambda) * emit_lp
                   - jax.lax.stop_gradient(fastemit_lambda * emit_lp))

    def step_t(alpha_prev, t):
        # horizontal (blank) move from alpha[t-1, u]
        from_blank = jnp.where(
            t > 0, alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :],
            jnp.where(jnp.arange(U1)[None, :] == 0, 0.0, neg_inf))

        # vertical (emit) moves within row t: sequential in u
        def step_u(carry, u):
            # carry = alpha[t, u-1]
            prev = carry
            horiz = from_blank[:, u]
            vert = jnp.where(
                u > 0,
                prev + emit_lp[:, t, jnp.maximum(u - 1, 0)],
                neg_inf)
            a = jnp.logaddexp(horiz, vert)
            a = jnp.where(t == 0,
                          jnp.where(u == 0, 0.0, vert), a)
            return a, a

        _, rows = jax.lax.scan(step_u, jnp.full((B,), neg_inf),
                               jnp.arange(U1))
        alpha_t = jnp.moveaxis(rows, 0, 1)            # [B, U+1]
        return alpha_t, alpha_t

    _, alphas = jax.lax.scan(step_t, jnp.zeros((B, U1), logp.dtype),
                             jnp.arange(T))
    alphas = jnp.moveaxis(alphas, 0, 1)               # [B, T, U+1]
    bidx = jnp.arange(B)
    t_last = logit_lengths - 1
    u_last = label_lengths
    final = alphas[bidx, t_last, u_last] + blank_lp[bidx, t_last, u_last]
    return -final


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """reference loss.py rnnt_loss — input [B, T, U+1, V] joint-network
    logits, label [B, U]."""
    out = _rnnt_loss(input, label, input_lengths, label_lengths,
                     blank=int(blank),
                     fastemit_lambda=float(fastemit_lambda))
    return apply("reduce_loss", lambda v, red=None: _reduce(v, red),
                 out, red=reduction)


# ---------------------------------------------------------- vision warps
@defop("affine_grid_op")
def _affine_grid(theta, *, out_shape, align_corners):
    N, C, H, W = out_shape

    def axis(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys, xs = jnp.meshgrid(axis(H), axis(W), indexing="ij")
    ones = jnp.ones_like(xs)
    base = jnp.stack([xs, ys, ones], axis=-1)         # [H, W, 3]
    grid = jnp.einsum("hwk,nck->nhwc", base.astype(theta.dtype), theta)
    return grid                                       # [N, H, W, 2]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference nn/functional/vision.py affine_grid — 2D only here
    (theta [N, 2, 3] -> grid [N, H, W, 2])."""
    shape = tuple(int(s) for s in (
        out_shape.numpy() if isinstance(out_shape, Tensor)
        else out_shape))
    if len(shape) != 4:
        raise NotImplementedError(
            "affine_grid supports 4-D out_shape (2D warps)")
    return _affine_grid(theta, out_shape=shape,
                        align_corners=bool(align_corners))


@defop("temporal_shift_op")
def _temporal_shift(x, *, seg_num, shift_ratio):
    NT, C, H, W = x.shape
    N = NT // seg_num
    v = x.reshape(N, seg_num, C, H, W)
    fold = int(C * shift_ratio)
    pad = jnp.zeros((N, 1, fold, H, W), x.dtype)
    # fold 0: shifted from t-1 (pad the first step)
    a = jnp.concatenate([pad, v[:, :-1, :fold]], axis=1)
    # fold 1: shifted from t+1
    b = jnp.concatenate([v[:, 1:, fold:2 * fold], pad], axis=1)
    rest = v[:, :, 2 * fold:]
    return jnp.concatenate([a, b, rest], axis=2).reshape(NT, C, H, W)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """reference nn/functional/extension.py temporal_shift."""
    if data_format != "NCHW":
        raise NotImplementedError("temporal_shift supports NCHW")
    return _temporal_shift(x, seg_num=int(seg_num),
                           shift_ratio=float(shift_ratio))


@defop("gather_tree_op")
def _gather_tree(ids, parents):
    T, B, beam = ids.shape

    def step(carry, t):
        beams = carry                                  # [B, beam]
        out = jnp.take_along_axis(ids[t], beams, axis=1)
        nxt = jnp.take_along_axis(parents[t], beams, axis=1)
        return nxt, out

    init = jnp.tile(jnp.arange(beam)[None, :], (B, 1))
    _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return outs[::-1]


def gather_tree(ids, parents):
    """reference nn/functional/extension.py gather_tree — backtrace beam
    ids along parent pointers, [T, B, beam]."""
    return _gather_tree(ids, parents)


def class_center_sample(label, num_classes, num_samples, group=None):
    """reference nn/functional/common.py class_center_sample — keep the
    positive classes, top up with random negatives to num_samples, and
    remap labels into the sampled index space. Host-side op (the output
    is a data-dependent *selection*; the reference runs it as a CUDA
    kernel feeding PartialFC) using the framework host seed stream."""
    from ...framework import random as frandom
    lab = np.asarray(label._value if isinstance(label, Tensor)
                     else label).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rng = np.random.default_rng(frandom.next_host_seed())
        rest = np.setdiff1d(np.arange(num_classes), pos,
                            assume_unique=False)
        extra = rng.choice(rest, size=num_samples - len(pos),
                           replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones((num_classes,), np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab]), stop_gradient=True),
            Tensor(jnp.asarray(sampled.astype(np.int64)),
                   stop_gradient=True))


# ---------------------------------------------------------- max-unpool
def _unpool_nd(x, indices, spatial_out, nd):
    """Scatter pooled values back to `spatial_out` positions given the
    per-(N, C) flattened argmax indices (the paddle mask convention)."""
    xv = x
    N, C = xv.shape[0], xv.shape[1]
    flat_sz = 1
    for s in spatial_out:
        flat_sz *= s
    xf = xv.reshape(N, C, -1)
    idxf = indices.reshape(N, C, -1)
    out = jnp.zeros((N, C, flat_sz), xv.dtype)
    n_i = jnp.arange(N)[:, None, None]
    c_i = jnp.arange(C)[None, :, None]
    out = out.at[n_i, c_i, idxf].set(xf)
    return out.reshape((N, C) + tuple(spatial_out))


def _resolve_unpool_out(in_spatial, kernel_size, stride, padding,
                        output_size, nd):
    ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * nd if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    if output_size is not None:
        out = tuple(int(s) for s in output_size)
        if len(out) > nd:                    # [N, C, ...] form accepted
            out = out[-nd:]
        return out
    return tuple((in_spatial[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                 for i in range(nd))


def _max_unpool(x, indices, kernel_size, stride, padding, output_size,
                nd, data_format):
    expected = {1: "NCL", 2: "NCHW", 3: "NCDHW"}[nd]
    if data_format != expected:
        raise NotImplementedError(
            f"max_unpool{nd}d supports {expected} only")
    spatial = tuple(x.shape[2:])
    out_sp = _resolve_unpool_out(spatial, kernel_size, stride, padding,
                                 output_size, nd)
    # the pool that produced `indices` must be reconstructible from
    # out_sp — otherwise indices can address cells outside the output
    # and jax's clipping scatter would corrupt silently (the reference
    # raises on inconsistent output_size too)
    ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * nd if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    for i in range(nd):
        back = (out_sp[i] + 2 * pd[i] - ks[i]) // st[i] + 1
        if back != spatial[i]:
            raise ValueError(
                f"max_unpool{nd}d: output_size {out_sp} is inconsistent "
                f"with pooled input {spatial} for kernel={ks}, "
                f"stride={st}, padding={pd}")
    return apply(f"max_unpool{nd}d_op",
                 lambda xv, iv, out_sp_=None, nd_=None: _unpool_nd(
                     xv, iv, out_sp_, nd_),
                 x, indices, out_sp_=out_sp, nd_=nd)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool1d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 1, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool2d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 2, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool3d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 3, data_format)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    """reference nn/functional/pooling.py adaptive_max_pool3d — bucket
    max over the three spatial axes; return_mask rides the divisible
    fast path (kernel == stride == in/out)."""
    from .pooling import _masked_max_pool, _tuplize
    outs = _tuplize(output_size, 3)
    spatial = tuple(int(s) for s in x.shape[2:])
    if return_mask:
        if any(spatial[i] % outs[i] for i in range(3)):
            raise NotImplementedError(
                "adaptive_max_pool3d(return_mask=True) needs input "
                "spatial dims divisible by output_size")
        ks = tuple(spatial[i] // outs[i] for i in range(3))
        return _masked_max_pool(x, ks, ks, 0, 3, "NCDHW",
                                "adaptive_max_pool3d_mask_op")

    @defop("adaptive_max_pool3d_op")
    def _amp3(xv, *, out_dhw):
        for i in range(3):
            axis = 2 + i
            size = xv.shape[axis]
            out = out_dhw[i]
            splits = [size * j // out for j in range(out + 1)]
            parts = [jnp.max(
                jax.lax.slice_in_dim(xv, splits[j], splits[j + 1],
                                     axis=axis), axis=axis,
                keepdims=True) for j in range(out)]
            xv = jnp.concatenate(parts, axis=axis)
        return xv

    return _amp3(x, out_dhw=outs)
