"""Convolution functionals.

Reference analog: python/paddle/nn/functional/conv.py → phi conv kernels
(cuDNN in the reference). Here convs lower to XLA's conv_general_dilated,
which maps directly onto the TPU MXU; layout assignment (NCHW→internal) is
XLA's job, so we keep Paddle's NCHW-default API unchanged.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import defop
from ...framework.tensor import Tensor


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return tuple((p, p) for p in padding)
    if len(padding) == 2 * n:
        return tuple((padding[2 * i], padding[2 * i + 1]) for i in range(n))
    # paddle also allows [[0,0],[0,0],[ph,ph],[pw,pw]]
    if len(padding) == n + 2:
        return tuple(tuple(p) for p in padding[2:])
    return tuple(tuple(p) for p in padding)


def _conv_nd(x, w, bias, stride, padding, dilation, groups, nd, data_format):
    chan_first = data_format.startswith("NC")
    if nd == 1:
        dn_spec = ("NCH", "OIH", "NCH") if chan_first else ("NHC", "OIH", "NHC")
    elif nd == 2:
        dn_spec = ("NCHW", "OIHW", "NCHW") if chan_first else \
            ("NHWC", "OIHW", "NHWC")
    else:
        dn_spec = ("NCDHW", "OIDHW", "NCDHW") if chan_first else \
            ("NDHWC", "OIDHW", "NDHWC")
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dn_spec)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[1 if chan_first else -1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


@defop("conv1d_op")
def _conv1d(x, w, b, stride, padding, dilation, groups, data_format):
    return _conv_nd(x, w, b, stride, padding, dilation, groups, 1, data_format)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv1d(x, weight, bias, _tuplize(stride, 1), _padding(padding, 1),
                   _tuplize(dilation, 1), int(groups), df)


@defop("conv2d_op")
def _conv2d(x, w, b, stride, padding, dilation, groups, data_format):
    return _conv_nd(x, w, b, stride, padding, dilation, groups, 2, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv2d(x, weight, bias, _tuplize(stride, 2), _padding(padding, 2),
                   _tuplize(dilation, 2), int(groups), data_format)


@defop("conv3d_op")
def _conv3d(x, w, b, stride, padding, dilation, groups, data_format):
    return _conv_nd(x, w, b, stride, padding, dilation, groups, 3, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv3d(x, weight, bias, _tuplize(stride, 3), _padding(padding, 3),
                   _tuplize(dilation, 3), int(groups), data_format)


def _conv_transpose_nd(x, w, bias, stride, padding, output_padding, dilation,
                       groups, nd, data_format):
    chan_first = data_format.startswith("NC")
    # paddle weight layout for transpose conv: [in, out/groups, *k].
    # Express as a forward conv on the stride-dilated input: flip the kernel
    # spatially and swap its channel axes to [out/groups, in, *k] (OI layout).
    if nd == 1:
        spec = ("NCH", "OIH", "NCH") if chan_first else ("NHC", "OIH", "NHC")
    elif nd == 2:
        spec = ("NCHW", "OIHW", "NCHW") if chan_first else \
            ("NHWC", "OIHW", "NHWC")
    else:
        spec = ("NCDHW", "OIDHW", "NCDHW") if chan_first else \
            ("NDHWC", "OIDHW", "NDHWC")
    w = jnp.swapaxes(jnp.flip(w, axis=tuple(range(2, 2 + nd))), 0, 1)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, spec)
    if isinstance(padding, str):
        pad = padding
    else:
        # conv_transpose padding semantics: derive from forward-conv padding
        pad = []
        k = w.shape[2:]
        for i in range(nd):
            eff_k = (k[i] - 1) * dilation[i] + 1
            lo = eff_k - 1 - padding[i][0]
            hi = eff_k - 1 - padding[i][1] + output_padding[i]
            pad.append((lo, hi))
        pad = tuple(pad)
    if groups > 1:
        xs = jnp.split(x, groups, axis=1 if chan_first else -1)
        ws = jnp.split(w, groups, axis=1)
        outs = [jax.lax.conv_general_dilated(
            xg, wg, window_strides=(1,) * nd, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn) for xg, wg in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1 if chan_first else -1)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * nd, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[1 if chan_first else -1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


@defop("conv1d_transpose_op")
def _conv1dt(x, w, b, stride, padding, output_padding, dilation, groups,
             data_format):
    return _conv_transpose_nd(x, w, b, stride, padding, output_padding,
                              dilation, groups, 1, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv1dt(x, weight, bias, _tuplize(stride, 1), _padding(padding, 1),
                    _tuplize(output_padding, 1), _tuplize(dilation, 1),
                    int(groups), df)


@defop("conv2d_transpose_op")
def _conv2dt(x, w, b, stride, padding, output_padding, dilation, groups,
             data_format):
    return _conv_transpose_nd(x, w, b, stride, padding, output_padding,
                              dilation, groups, 2, data_format)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv2dt(x, weight, bias, _tuplize(stride, 2),
                    _padding(padding, 2), _tuplize(output_padding, 2),
                    _tuplize(dilation, 2), int(groups), data_format)


@defop("conv3d_transpose_op")
def _conv3dt(x, w, b, stride, padding, output_padding, dilation, groups,
             data_format):
    return _conv_transpose_nd(x, w, b, stride, padding, output_padding,
                              dilation, groups, 3, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv3dt(x, weight, bias, _tuplize(stride, 3),
                    _padding(padding, 3), _tuplize(output_padding, 3),
                    _tuplize(dilation, 3), int(groups), data_format)
