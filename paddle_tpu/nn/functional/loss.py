"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy fuses log_softmax+gather in one op body — XLA emits the same
fused softmax-xent the reference's softmax_with_cross_entropy CUDA kernel
hand-writes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework.dispatch import defop
from ...framework.tensor import Tensor


def _reduce(loss, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(loss) / weight_sum
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop("cross_entropy_hard")
def _cross_entropy_hard(input, label, weight, ignore_index, reduction, axis,
                        use_softmax, label_smoothing):
    logits = input
    if axis != -1 and axis != input.ndim - 1:
        logits = jnp.moveaxis(logits, axis, -1)
        if label.ndim == input.ndim:
            label = jnp.moveaxis(label, axis, -1)
    squeeze_label = (label.ndim == logits.ndim and label.shape[-1] == 1)
    if squeeze_label:
        label = label[..., 0]
    n_class = logits.shape[-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1) \
        if use_softmax else jnp.log(jnp.maximum(logits, 1e-37)).astype(jnp.float32)
    valid = (label != ignore_index)
    safe_label = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(logp, safe_label[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    if label_smoothing > 0.0:
        smooth = jnp.mean(logp, axis=-1)
        picked = (1.0 - label_smoothing) * picked + label_smoothing * smooth
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe_label, axis=0).astype(jnp.float32)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    else:
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
    return _reduce(loss, reduction)


@defop("cross_entropy_soft")
def _cross_entropy_soft(input, label, reduction, axis, use_softmax,
                        label_smoothing):
    logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis) \
        if use_softmax else jnp.log(jnp.maximum(input, 1e-37)).astype(jnp.float32)
    lab = label.astype(jnp.float32)
    if label_smoothing > 0.0:
        n = input.shape[axis]
        lab = (1.0 - label_smoothing) * lab + label_smoothing / n
    loss = -jnp.sum(lab * logp, axis=axis)
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    if soft_label:
        return _cross_entropy_soft(input, label, reduction, int(axis),
                                   bool(use_softmax), float(label_smoothing))
    return _cross_entropy_hard(input, label, weight, int(ignore_index),
                               reduction, int(axis), bool(use_softmax),
                               float(label_smoothing))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from ..functional.activation import softmax as softmax_fn
    from ...ops.manipulation import unsqueeze
    if not soft_label:
        loss = unsqueeze(loss, -1)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


@defop("mse_loss_op")
def _mse_loss(input, label, reduction):
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _mse_loss(input, label, reduction)


@defop("l1_loss_op")
def _l1_loss(input, label, reduction):
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _l1_loss(input, label, reduction)


@defop("smooth_l1_loss_op")
def _smooth_l1(input, label, reduction, delta):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    return _smooth_l1(input, label, reduction, float(delta))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    @defop("huber_loss_op")
    def _huber(input, label, reduction, delta):
        diff = jnp.abs(input - label)
        loss = jnp.where(diff <= delta, 0.5 * diff * diff,
                         delta * (diff - 0.5 * delta))
        return _reduce(loss, reduction)
    return _huber(input, label, reduction, float(delta))


@defop("nll_loss_op")
def _nll_loss(input, label, weight, ignore_index, reduction):
    valid = (label != ignore_index)
    safe = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(input, safe[..., None].astype(jnp.int32)
                                 if input.ndim == label.ndim + 1 else safe,
                                 axis=1 if input.ndim > 1 else 0)
    if input.ndim == label.ndim + 1:
        picked = jnp.squeeze(picked, axis=1)
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe, axis=0)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.sum(jnp.where(valid, w, 0.0))
    loss = jnp.where(valid, loss, 0.0)
    return _reduce(loss, reduction)


@defop("nll_loss_gather")
def _nll_gather(input, label, weight, ignore_index, reduction):  # noqa: A002
    valid = (label != ignore_index)
    safe = jnp.where(valid, label, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(input, safe[:, None, ...], axis=1)
    picked = jnp.squeeze(picked, axis=1)
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe, axis=0)
        loss = jnp.where(valid, loss * w, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.sum(jnp.where(valid, w, 0.0))
    else:
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            # total_weight = count of non-ignored labels (paddle/torch)
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
    loss = jnp.where(valid, loss, 0.0)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    # input: log-probabilities [N, C, ...]; gather along class dim
    return _nll_gather(input, label, weight, int(ignore_index), reduction)


@defop("bce_loss_op")
def _bce(input, label, weight, reduction):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1.0 - label) * jnp.log(jnp.maximum(1.0 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    return _bce(input, label, weight, reduction)


@defop("bce_logits_op")
def _bce_logits(logit, label, weight, pos_weight, reduction):
    log_sig = jax.nn.log_sigmoid(logit)
    log_sig_neg = jax.nn.log_sigmoid(-logit)
    if pos_weight is not None:
        loss = -(pos_weight * label * log_sig + (1.0 - label) * log_sig_neg)
    else:
        loss = -(label * log_sig + (1.0 - label) * log_sig_neg)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return _bce_logits(logit, label, weight, pos_weight, reduction)


@defop("kl_div_op")
def _kl_div(input, label, reduction, log_target):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    return _kl_div(input, label, reduction, bool(log_target))


@defop("margin_ranking_op")
def _margin_ranking(input, other, label, margin, reduction):
    loss = jnp.maximum(0.0, -label * (input - other) + margin)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return _margin_ranking(input, other, label, float(margin), reduction)


@defop("hinge_embedding_op")
def _hinge_embedding(input, label, margin, reduction):
    loss = jnp.where(label == 1.0, input,
                     jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",  # noqa: A002
                         name=None):
    return _hinge_embedding(input, label, float(margin), reduction)


@defop("cosine_embedding_op")
def _cosine_embedding(input1, input2, label, margin, reduction):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1.0 - cos,
                     jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    return _cosine_embedding(input1, input2, label, float(margin), reduction)


@defop("triplet_margin_op")
def _triplet_margin(anchor, positive, negative, margin, p, eps, swap,
                    reduction):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b + eps) ** p, axis=-1) ** (1.0 / p)
    d_pos = dist(anchor, positive)
    d_neg = dist(anchor, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(loss, reduction)


def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    return _triplet_margin(anchor, positive, negative, float(margin),
                           float(p), float(epsilon), bool(swap), reduction)


@defop("log_loss_op")
def _log_loss(input, label, epsilon):
    return -label * jnp.log(input + epsilon) - \
        (1.0 - label) * jnp.log(1.0 - input + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return _log_loss(input, label, float(epsilon))


@defop("square_error_cost_op")
def _square_error_cost(input, label):
    return jnp.square(input - label)


def square_error_cost(input, label):  # noqa: A002
    return _square_error_cost(input, label)


@defop("sigmoid_focal_op")
def _sigmoid_focal(logit, label, normalizer, alpha, gamma, reduction):
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit) +
           (1 - label) * jax.nn.log_sigmoid(-logit))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return _sigmoid_focal(logit, label, normalizer, float(alpha),
                          float(gamma), reduction)


@defop("ctc_loss_op")
def _ctc_loss(log_probs, labels, input_lengths, label_lengths, blank,
              reduction):
    # log_probs: [T, N, C] paddle layout
    import optax
    lp = jnp.moveaxis(log_probs, 0, 1)  # [N, T, C]
    t = lp.shape[1]
    lmax = labels.shape[1]
    logit_pad = (jnp.arange(t)[None, :] >= input_lengths[:, None]).astype(
        jnp.float32)
    label_pad = (jnp.arange(lmax)[None, :] >= label_lengths[:, None]).astype(
        jnp.float32)
    per_seq = optax.ctc_loss(lp, logit_pad, labels, label_pad,
                             blank_id=blank)
    if reduction == "mean":
        return jnp.mean(per_seq / jnp.maximum(label_lengths, 1))
    return _reduce(per_seq, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    return _ctc_loss(log_probs, labels, input_lengths, label_lengths,
                     int(blank), reduction)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    @defop("npair_loss_op")
    def _npair(anchor, positive, labels, l2_reg):
        reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1)) +
                        jnp.mean(jnp.sum(jnp.square(positive), axis=1))) / 2
        sim = anchor @ positive.T
        lab = labels[:, None] == labels[None, :]
        lab = lab.astype(jnp.float32)
        lab = lab / jnp.sum(lab, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(-lab * jax.nn.log_softmax(sim, axis=1),
                                axis=1))
        return xent + reg
    return _npair(anchor, positive, labels, float(l2_reg))
