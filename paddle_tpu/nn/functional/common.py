"""Common functionals: linear, embedding, dropout, normalize, interpolate, pad.

Reference analog: python/paddle/nn/functional/common.py. Dropout draws its key
from the global counter-based PRNG so the mask is identical under tape
recompute (framework/random.py) and threads through to_static traces.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework.dispatch import defop, apply
from ...framework.random import next_key
from ...framework.tensor import Tensor


@defop("linear")
def _linear(x, w, b):
    y = jnp.matmul(x, w)
    if b is not None:
        y = y + b
    return y


@defop("linear_nobias")
def _linear_nb(x, w):
    return jnp.matmul(x, w)


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return _linear_nb(x, weight)
    return _linear(x, weight, bias)


@defop("embedding_op")
def _embedding(weight, x, padding_idx, sparse):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out).astype(weight.dtype)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _embedding(weight, x,
                      None if padding_idx is None else int(padding_idx),
                      bool(sparse))


@defop("dropout_op")
def _dropout(x, key, p, training, mode, axis):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    shape = list(x.shape)
    if axis is not None:
        for i in range(len(shape)):
            if i not in axis:
                shape[i] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if axis is not None and not isinstance(axis, (list, tuple)):
        axis = (int(axis),)
    elif axis is not None:
        axis = tuple(int(a) for a in axis)
    return _dropout(x, next_key(), float(p), bool(training), mode, axis)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x

    @defop("alpha_dropout")
    def _alpha_dropout(x, key, p):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = 1.0 - p
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(key, keep, x.shape)
        return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)
    return _alpha_dropout(x, next_key(), float(p))


@defop("normalize_op")
def _normalize(x, p, axis, epsilon):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize(x, float(p), int(axis), float(epsilon))


@defop("label_smooth_op")
def _label_smooth(label, epsilon, prior=None):
    n = label.shape[-1]
    if prior is None:
        return (1 - epsilon) * label + epsilon / n
    return (1 - epsilon) * label + epsilon * prior


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        def _ls(label, prior, epsilon):
            return (1 - epsilon) * label + epsilon * prior
        return apply("label_smooth_prior", _ls, label, prior_dist,
                     epsilon=float(epsilon))
    return _label_smooth(label, float(epsilon))


from ...ops.manipulation import pad  # noqa: E402,F401  (F.pad is ops.pad)


@defop("cosine_similarity_op")
def _cosine_similarity(x1, x2, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _cosine_similarity(x1, x2, int(axis), float(eps))


@defop("pixel_shuffle_op")
def _pixel_shuffle(x, upscale_factor, data_format):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        oc = c // (r * r)
        x = x.reshape(n, oc, r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, oc, h * r, w * r)
    n, h, w, c = x.shape
    oc = c // (r * r)
    x = x.reshape(n, h, w, r, r, oc)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, oc)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle(x, int(upscale_factor), data_format)


@defop("pixel_unshuffle_op")
def _pixel_unshuffle(x, r, data_format):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = x.transpose(0, 2, 4, 5, 1, 3)
    return x.reshape(n, h // r, w // r, c * r * r)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _pixel_unshuffle(x, int(downscale_factor), data_format)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    @defop("channel_shuffle_op")
    def _channel_shuffle(x, groups, data_format):
        if data_format == "NCHW":
            n, c, h, w = x.shape
            x = x.reshape(n, groups, c // groups, h, w)
            x = x.transpose(0, 2, 1, 3, 4)
            return x.reshape(n, c, h, w)
        n, h, w, c = x.shape
        x = x.reshape(n, h, w, groups, c // groups)
        x = x.transpose(0, 1, 2, 4, 3)
        return x.reshape(n, h, w, c)
    return _channel_shuffle(x, int(groups), data_format)


@defop("interpolate_op")
def _interpolate(x, size, mode, align_corners, data_format):
    # channels-first spatial resize via jax.image
    spatial_dims = len(size)
    if data_format.startswith("NC"):
        out_shape = x.shape[:2] + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    return jax.image.resize(x, out_shape, method=method).astype(x.dtype)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    nd = x.ndim - 2
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size / scale_factor must be set")
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * nd
        spatial = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
        size = [int(s * f) for s, f in zip(spatial, sf)]
    if isinstance(size, Tensor):
        size = [int(v) for v in size.numpy().reshape(-1)]
    size = tuple(int(s.item() if isinstance(s, Tensor) else s) for s in size)
    return _interpolate(x, size, mode, bool(align_corners), data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@defop("unfold_op")
def _unfold(x, kernel_sizes, strides, paddings, dilations):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    ph, pw = paddings[0], paddings[1]
    dh, dw = dilations
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(
                x[:, :, i * dh:i * dh + oh * sh:sh,
                  j * dw:j * dw + ow * sw:sw])
    out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
    return out.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    return _unfold(x, _pair(kernel_sizes), _pair(strides), _pair(paddings),
                   _pair(dilations))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    @defop("fold_op")
    def _fold(x, output_sizes, kernel_sizes, strides, paddings, dilations):
        n, ckk, l = x.shape
        kh, kw = kernel_sizes
        c = ckk // (kh * kw)
        oh_pad = output_sizes[0] + 2 * paddings[0]
        ow_pad = output_sizes[1] + 2 * paddings[1]
        sh, sw = strides
        dh, dw = dilations
        nh = (oh_pad - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow_pad - (dw * (kw - 1) + 1)) // sw + 1
        x = x.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh_pad, ow_pad), x.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + nh * sh:sh,
                             j * dw:j * dw + nw * sw:sw].add(x[:, :, i, j])
        return out[:, :, paddings[0]:oh_pad - paddings[0],
                   paddings[1]:ow_pad - paddings[1]]
    return _fold(x, _pair(output_sizes), _pair(kernel_sizes), _pair(strides),
                 _pair(paddings), _pair(dilations))


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(x1, x2, w, b=None):
        out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
        if b is not None:
            out = out + b
        return out
    if bias is None:
        return apply("bilinear_nb", lambda a, b, w: _bilinear(a, b, w),
                     x1, x2, weight)
    return apply("bilinear", _bilinear, x1, x2, weight, bias)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        maxlen = int(lengths.numpy().max())

    @defop("sequence_mask_op")
    def _sequence_mask(lengths, maxlen, dtype):
        r = jnp.arange(maxlen)
        return (r[None, :] < lengths[..., None]).astype(dtype)
    return _sequence_mask(lengths, int(maxlen), dtypes.convert_dtype(dtype))


@defop("grid_sample_op")
def _grid_sample(x, grid, mode, padding_mode, align_corners):
    N, C, H, W = x.shape
    gx, gy = grid[..., 0], grid[..., 1]          # [N,Hg,Wg]

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) * (size - 1) / 2.0
        return ((g + 1.0) * size - 1.0) / 2.0

    fx, fy = unnorm(gx, W), unnorm(gy, H)

    def reflect(v, lo, hi):
        # triangle wave into [lo, hi]: lo→lo, hi→hi, hi+d→hi-d
        rng = hi - lo
        if rng <= 0:
            return jnp.full_like(v, lo)
        t = jnp.mod(v - lo, 2 * rng)
        return lo + (rng - jnp.abs(t - rng))

    if padding_mode == "reflection":
        if align_corners:
            fx = reflect(fx, 0.0, W - 1.0)
            fy = reflect(fy, 0.0, H - 1.0)
        else:
            fx = jnp.clip(reflect(fx, -0.5, W - 0.5), 0, W - 1)
            fy = jnp.clip(reflect(fy, -0.5, H - 0.5), 0, H - 1)

    def sample(ix, iy):
        # gather x[n, :, iy, ix] with out-of-range handling
        inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0)
               & (iy <= H - 1))                  # [N,Hg,Wg]
        ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        nidx = jnp.arange(N)[:, None, None]
        vals = x[nidx, :, iyc, ixc]              # [N,Hg,Wg,C]
        if padding_mode == "zeros":
            vals = jnp.where(inb[..., None], vals, 0.0)
        return vals, inb

    if mode == "nearest":
        vals, _ = sample(jnp.round(fx), jnp.round(fy))
        return jnp.moveaxis(vals, -1, 1).astype(x.dtype)

    x0, y0 = jnp.floor(fx), jnp.floor(fy)
    x1, y1 = x0 + 1, y0 + 1
    wx1, wy1 = fx - x0, fy - y0
    wx0, wy0 = 1.0 - wx1, 1.0 - wy1
    out = 0.0
    for ix, wx in ((x0, wx0), (x1, wx1)):
        for iy, wy in ((y0, wy0), (y1, wy1)):
            vals, _ = sample(ix, iy)
            out = out + vals * (wx * wy)[..., None]
    return jnp.moveaxis(out, -1, 1).astype(x.dtype)



def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Spatial sampling by a flow field (reference: ops.yaml `grid_sample`,
    phi grid_sample_kernel). x [N,C,H,W], grid [N,Hg,Wg,2] with xy in
    [-1,1] → [N,C,Hg,Wg]. Gather+lerp — XLA fuses it into one kernel."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, "
                         f"got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode!r}")
    return _grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                        align_corners=bool(align_corners))
