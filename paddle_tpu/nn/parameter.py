"""Parameter — a trainable Tensor.

Reference analog: EagerParamBase (python/paddle/fluid/framework.py) — a Tensor
with trainable/optimize metadata that Layers collect.
"""
from __future__ import annotations

import itertools

from ..framework.tensor import Tensor

_param_counter = itertools.count()


class Parameter(Tensor):
    # NOTE: sharding_spec slot lives on the Tensor base class now
    __slots__ = ("trainable", "optimize_attr", "regularizer",
                 "do_model_average", "need_clip", "is_distributed")

    def __init__(self, value, trainable: bool = True, name: str = ""):
        super().__init__(value, stop_gradient=not trainable,
                         name=name or f"param_{next(_param_counter)}")
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False
        # PartitionSpec-style sharding annotation consumed by
        # paddle_tpu.parallel when building pjit shardings (TP/FSDP axes).
        self.sharding_spec = None
        self.persistable = True
        self.is_leaf_override = True

    @property
    def requires_grad(self):
        return not self.stop_gradient

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
