"""Gradient clipping (reference: python/paddle/nn/clip.py — ClipGradByNorm,
ClipGradByValue, ClipGradByGlobalNorm). Applied by optimizers before update;
the global-norm variant runs as one fused jitted pytree computation.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


class ClipGradBase:
    def _clip_values(self, grads):
        """grads: list of jax arrays → list of jax arrays (pure; traceable)."""
        raise NotImplementedError

    def __call__(self, params_grads):
        # paddle-style interface: list[(param, grad Tensor)]
        grads = [g._value for _, g in params_grads]
        clipped = self._clip_values(grads)
        return [(p, Tensor(g, stop_gradient=True))
                for (p, _), g in zip(params_grads, clipped)]


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip_values(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_values(self, grads):
        out = []
        for g in grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            factor = jnp.where(norm > self.clip_norm,
                               self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g * factor).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _clip_values(self, grads):
        gn_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads)
        gnorm = jnp.sqrt(gn_sq)
        factor = jnp.where(gnorm > self.clip_norm,
                           self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return [(g * factor).astype(g.dtype) for g in grads]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad._value for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad._value * factor).astype(p.grad.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
