"""Seq2seq decoding (reference python/paddle/nn/decode.py:30 Decoder,
:150 BeamSearchDecoder, :994 dynamic_decode).

TPU-native shape discipline: every step works on [batch*beam, ...]
tensors with STATIC shapes; `finished` is a boolean mask (no dynamic
batch shrinking), and the loop is the host-driven eager loop the
reference's while_op implements — each step body is jit-compiled
through the dispatch layer, so steady-state decoding replays compiled
executables."""
from __future__ import annotations

import collections

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class Decoder:
    """reference decode.py:30 — the initialize/step/finalize protocol."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """reference decode.py:150 — wraps an RNN cell; candidate scoring by
    accumulated log-probability, end_token freezes a beam."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished",
                         "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam tiling helpers (the reference's public static methods) -----
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeat-interleave."""
        v = _v(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _merge(self, v):
        return v.reshape((-1,) + v.shape[2:])          # [B,beam,...]→

    def _split(self, v):
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    # -- protocol --------------------------------------------------------
    def initialize(self, initial_cell_states):
        states = initial_cell_states
        leaves = states if isinstance(states, (tuple, list)) else [states]
        batch = _v(leaves[0]).shape[0]
        self._batch = batch
        tiled = [Tensor(jnp.repeat(_v(s)[:, None], self.beam_size,
                                   axis=1).reshape(
                     (-1,) + _v(s).shape[1:])) for s in leaves]
        cell_states = (type(states)(tiled)
                       if isinstance(states, (tuple, list)) else tiled[0])
        # only beam 0 starts live (log_prob 0); the rest -inf so the
        # first topk doesn't pick duplicate start beams
        log_probs = jnp.where(
            jnp.arange(self.beam_size)[None, :] == 0, 0.0, -1e30)
        log_probs = jnp.tile(log_probs, (batch, 1))
        init_ids = Tensor(jnp.full((batch * self.beam_size,),
                                   self.start_token, jnp.int32))
        init_inputs = (self.embedding_fn(init_ids)
                       if self.embedding_fn else init_ids)
        state = self.StateWrapper(
            cell_states, Tensor(log_probs),
            Tensor(jnp.zeros((batch, self.beam_size), bool)),
            Tensor(jnp.zeros((batch, self.beam_size), jnp.int32)))
        return init_inputs, state, Tensor(
            jnp.zeros((batch, self.beam_size), bool))

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell_states = self.cell(inputs,
                                               states.cell_states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _v(cell_out)                         # [B*beam, V]
        V = logits.shape[-1]
        shifted = logits - logits.max(-1, keepdims=True)
        logp = shifted - jnp.log(jnp.sum(jnp.exp(shifted), -1,
                                         keepdims=True))
        logp = self._split(logp)                      # [B, beam, V]
        prev = _v(states.log_probs)[:, :, None]
        finished = _v(states.finished)
        # finished beams only extend with end_token at zero cost
        end_only = jnp.full((V,), -1e30).at[self.end_token].set(0.0)
        cand = jnp.where(finished[:, :, None], end_only[None, None, :],
                         logp) + prev
        flat = cand.reshape(cand.shape[0], -1)        # [B, beam*V]
        topk_scores, topk_idx = _topk(flat, self.beam_size)
        parent = topk_idx // V                        # [B, beam]
        token = topk_idx % V
        B = flat.shape[0]
        gather = (jnp.arange(B)[:, None] * self.beam_size + parent
                  ).reshape(-1)

        def regather(s):
            return Tensor(_v(s)[gather])

        leaves = (next_cell_states
                  if isinstance(next_cell_states, (tuple, list))
                  else [next_cell_states])
        new_leaves = [regather(s) for s in leaves]
        cell_states = (type(next_cell_states)(new_leaves)
                       if isinstance(next_cell_states, (tuple, list))
                       else new_leaves[0])
        was_finished = finished.reshape(-1)[gather].reshape(
            B, self.beam_size)
        now_finished = was_finished | (token == self.end_token)
        lengths = _v(states.lengths).reshape(-1)[gather].reshape(
            B, self.beam_size)
        lengths = jnp.where(was_finished, lengths, lengths + 1)

        out = self.OutputWrapper(Tensor(topk_scores),
                                 Tensor(token.astype(jnp.int32)),
                                 Tensor(parent.astype(jnp.int32)))
        next_state = self.StateWrapper(cell_states, Tensor(topk_scores),
                                       Tensor(now_finished),
                                       Tensor(lengths))
        flat_tokens = Tensor(token.reshape(-1).astype(jnp.int32))
        next_inputs = (self.embedding_fn(flat_tokens)
                       if self.embedding_fn else flat_tokens)
        return out, next_state, next_inputs, Tensor(now_finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace predicted ids through parent pointers
        (gather_tree)."""
        from .functional import gather_tree
        ids = jnp.stack([_v(o.predicted_ids) for o in outputs])
        parents = jnp.stack([_v(o.parent_ids) for o in outputs])
        traced = gather_tree(Tensor(ids), Tensor(parents))
        return traced, final_states

    @property
    def tracks_own_finished(self):
        return True


def _topk(x, k):
    import jax
    return jax.lax.top_k(x, k)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference decode.py:994 — run decoder.step until every sequence
    finishes or max_step_num; returns (outputs, final_states[, length])."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    while True:
        out, states, inputs, finished = decoder.step(step, inputs,
                                                     states, **kwargs)
        outputs.append(out)
        step += 1
        if bool(np.asarray(_v(finished)).all()):
            break
        if max_step_num is not None and step > int(max_step_num):
            break
    final, final_states = decoder.finalize(outputs, states, None)
    if not output_time_major:
        final = Tensor(jnp.moveaxis(_v(final), 0, 1))
    if return_length:
        return final, final_states, final_states.lengths
    return final, final_states
