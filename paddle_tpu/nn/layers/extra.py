"""nn layer long-tail parity (reference python/paddle/nn/__init__.py
names missing from the v1 surface): loss-layer wrappers over
functional/extra.py, the max-unpool family, AdaptiveMaxPool3D,
Softmax2D, Unflatten."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F

__all__ = [
    "PoissonNLLLoss", "SoftMarginLoss", "MultiLabelSoftMarginLoss",
    "MultiMarginLoss", "TripletMarginWithDistanceLoss",
    "GaussianNLLLoss", "HSigmoidLoss", "RNNTLoss", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "Softmax2D",
    "Unflatten",
]


class PoissonNLLLoss(Layer):
    """reference nn/layer/loss.py PoissonNLLLoss."""

    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        log_input, full, epsilon, reduction = self._args
        return F.poisson_nll_loss(input, label, log_input=log_input,
                                  full=full, epsilon=epsilon,
                                  reduction=reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label,
                                  reduction=self._reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(
            input, label, weight=self._weight,
            reduction=self._reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, margin, weight, reduction = self._args
        return F.multi_margin_loss(input, label, p=p, margin=margin,
                                   weight=weight, reduction=reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        fn, margin, swap, reduction = self._args
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, distance_function=fn,
            margin=margin, swap=swap, reduction=reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        full, epsilon, reduction = self._args
        return F.gaussian_nll_loss(input, label, variance, full=full,
                                   epsilon=epsilon, reduction=reduction)


class HSigmoidLoss(Layer):
    """reference nn/layer/loss.py HSigmoidLoss — owns the internal-node
    weight [num_classes-1, feature_size] (SimpleCode tree) unless
    custom path tables supply a larger node space."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must not be less than 2 "
                             "with default tree")
        self._num_classes = num_classes
        self._is_custom = is_custom
        # default tree has num_classes - 1 internal nodes; custom trees
        # may address up to num_classes nodes
        rows = num_classes if is_custom else num_classes - 1
        # SimpleCode indices reach 2*num_classes-2 internal slots in the
        # worst (non-power-of-two) case — size generously like the
        # reference's C (=num_classes) x D parameterization
        rows = max(rows, 2 * num_classes - 1)
        self.weight = self.create_parameter((rows, feature_size),
                                            attr=weight_attr)
        self.bias = self.create_parameter((rows, 1), attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias,
                               path_table=path_table,
                               path_code=path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        blank, fe, reduction = self._args
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=blank, fastemit_lambda=fe,
                           reduction=reduction)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size,
                                     return_mask=self._return_mask)


class _MaxUnPoolBase(Layer):
    _nd = 2
    _fmt = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding,
                      data_format or self._fmt, output_size)

    def forward(self, x, indices):
        k, s, p, fmt, out = self._args
        fn = getattr(F, f"max_unpool{self._nd}d")
        return fn(x, indices, k, stride=s, padding=p, data_format=fmt,
                  output_size=out)


class MaxUnPool1D(_MaxUnPoolBase):
    _nd = 1
    _fmt = "NCL"


class MaxUnPool2D(_MaxUnPoolBase):
    _nd = 2
    _fmt = "NCHW"


class MaxUnPool3D(_MaxUnPoolBase):
    _nd = 3
    _fmt = "NCDHW"


class Softmax2D(Layer):
    """reference nn/layer/activation.py Softmax2D — softmax over the
    channel axis of NCHW (or CHW) inputs."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D requires a 3D or 4D tensor as input, "
                f"got {x.ndim}")
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    """reference nn/layer/common.py Unflatten — expand `axis` into
    `shape`."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis = axis
        self._shape = list(shape)

    def forward(self, x):
        from ...ops.manipulation import reshape
        axis = self._axis % x.ndim
        new_shape = (list(x.shape[:axis]) + self._shape
                     + list(x.shape[axis + 1:]))
        return reshape(x, new_shape)

    def extra_repr(self):
        return f"axis={self._axis}, shape={self._shape}"
