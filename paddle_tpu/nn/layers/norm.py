"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...framework import dtype as dtypes
from ...framework.tensor import Tensor
from ..layer import Layer
from .. import functional as F
from .. import initializer as I


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-era addition (not in the reference snapshot): used by the GPT
    flagship; sequence-parallel friendly (no mean subtraction)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(
            np.zeros(num_features, np.float32), stop_gradient=True))
        self.register_buffer("_variance", Tensor(
            np.ones(num_features, np.float32), stop_gradient=True))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU under pjit, batch stats are computed over the *global* batch by
    XLA collectives automatically when the batch axis is sharded — so
    SyncBatchNorm degenerates to BatchNorm inside a sharded computation
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm / NCCL allreduce
    of stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        raise NotImplementedError(
            "SpectralNorm: use paddle_tpu.nn.utils.spectral_norm")
