"""Transformer layer stack.

Reference analog: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/Decoder) and the fused variants in
python/paddle/incubate/nn/layer/fused_transformer.py:193,498,726.

TPU-native shape of this file: there is no separate Fused* hierarchy
because fusion is the compiler's job here — attention lands on
F.scaled_dot_product_attention (one fused kernel under the dispatch
layer) and XLA fuses the FFN matmul chain on its own; compat aliases for
the reference's Fused* names live in paddle_tpu.incubate. The pre/post
LayerNorm residual wiring, which the reference spells out longhand in
every sublayer, is factored into one `_residual` helper so the encoder
and decoder layers state only their sublayer bodies.
"""
from __future__ import annotations

import copy
from collections import namedtuple

from ..layer import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from .. import functional as F


def _convert_attention_mask(attn_mask, dtype):
    """Paddle contract: bool masks select, float masks add. Both forms
    pass through — F.scaled_dot_product_attention branches on dtype."""
    return attn_mask


def _residual(x, sublayer, norm, dropout, pre_norm):
    """One residual sublayer with the normalize_before toggle:
    pre-norm  -> x + drop(f(norm(x)))
    post-norm -> norm(x + drop(f(x)))
    """
    if pre_norm:
        return x + dropout(sublayer(norm(x)))
    return norm(x + dropout(sublayer(x)))


class MultiHeadAttention(Layer):
    """reference: python/paddle/nn/layer/transformer.py MultiHeadAttention."""

    Cache = namedtuple("Cache", ["k", "v"])
    StaticCache = namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads {num_heads} must evenly divide "
                f"embed_dim {embed_dim}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.kdim = kdim if kdim is not None else embed_dim
        self.vdim = vdim if vdim is not None else embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        mk = lambda d_in: Linear(d_in, embed_dim, weight_attr, bias_attr)  # noqa: E731
        self.q_proj = mk(embed_dim)
        self.k_proj = mk(self.kdim)
        self.v_proj = mk(self.vdim)
        self.out_proj = mk(embed_dim)

    def _heads(self, x):
        """[B, S, E] -> [B, S, H, hd] (the fused-attention layout)."""
        from ...ops.manipulation import reshape
        return reshape(x, [x.shape[0], x.shape[1], self.num_heads,
                           self.head_dim])

    def _project_kv(self, key, value, cache):
        """Resolve k/v heads through the cache protocol:
        - StaticCache: precomputed cross-attention k/v, reused as-is;
        - Cache: grow the autoregressive k/v along the time axis;
        - None: plain projection. Returns (k, v, updated_cache)."""
        from ...ops.manipulation import concat
        if isinstance(cache, MultiHeadAttention.StaticCache):
            return cache.k, cache.v, cache
        k = self._heads(self.k_proj(key))
        v = self._heads(self.v_proj(value))
        if isinstance(cache, MultiHeadAttention.Cache):
            k = concat([cache.k, k], axis=1)
            v = concat([cache.v, v], axis=1)
            return k, v, MultiHeadAttention.Cache(k, v)
        return k, v, None

    def gen_cache(self, key, value=None, type=Cache):  # noqa: A002
        """Build the decode-time cache (reference gen_cache contract):
        StaticCache projects `key`/`value` once for cross-attention; the
        default Cache starts empty (S=0) and grows per step; passing
        both tensors seeds a Cache directly."""
        from ...ops.creation import zeros
        if type == MultiHeadAttention.StaticCache:
            v_src = key if value is None else value
            return self.StaticCache(self._heads(self.k_proj(key)),
                                    self._heads(self.v_proj(v_src)))
        if value is not None:
            return self.Cache(key, value)
        empty = [key.shape[0], 0, self.num_heads, self.head_dim]
        return self.Cache(zeros(empty, key.dtype), zeros(empty, key.dtype))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ...ops.manipulation import reshape
        # reference defaulting: BOTH omitted tensors fall back to query
        # (an omitted value does NOT follow key)
        key = key if key is not None else query
        value = value if value is not None else query
        q = self._heads(self.q_proj(query))
        k, v, new_cache = self._project_kv(key, value, cache)
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=_convert_attention_mask(attn_mask, q.dtype),
            dropout_p=self.dropout, training=self.training)
        ctx = reshape(ctx, [ctx.shape[0], ctx.shape[1], self.embed_dim])
        out = self.out_proj(ctx)
        # the fused kernel never materializes the probability matrix, so
        # need_weights yields None (documented reference behavior for the
        # fused path)
        outs = (out,)
        if self.need_weights:
            outs += (None,)
        if cache is not None:
            outs += (new_cache,)
        return outs if len(outs) > 1 else out


class _FFNMixin:
    """linear -> activation -> dropout -> linear, shared by the encoder
    and decoder layers. A mixin (not a sub-Layer) so the linears stay
    registered once under the reference's attribute names — state_dict
    keys and parameter traversal match the reference exactly."""

    def _init_ffn(self, d_model, d_hidden, drop, activation, weight_attr,
                  bias_attr):
        self.linear1 = Linear(d_model, d_hidden, weight_attr, bias_attr)
        self.linear2 = Linear(d_hidden, d_model, weight_attr, bias_attr)
        self.dropout = Dropout(drop)
        self.activation = getattr(F, activation)

    def _ffn(self, x):
        return self.linear2(self.dropout(self.activation(self.linear1(x))))


class TransformerEncoderLayer(Layer, _FFNMixin):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead,
            dropout=dropout if attn_dropout is None else attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self._init_ffn(d_model, dim_feedforward,
                       dropout if act_dropout is None else act_dropout,
                       activation, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)

    def forward(self, src, src_mask=None, cache=None):
        new_cache = None

        def attn(x):
            nonlocal new_cache
            if cache is None:
                return self.self_attn(x, x, x, src_mask)
            y, new_cache = self.self_attn(x, x, x, src_mask, cache)
            return y

        pre = self.normalize_before
        src = _residual(src, attn, self.norm1, self.dropout1, pre)
        src = _residual(src, self._ffn, self.norm2, self.dropout2, pre)
        return src if cache is None else (src, new_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerDecoderLayer(Layer, _FFNMixin):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        adrop = dropout if attn_dropout is None else attn_dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, adrop,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, adrop,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self._init_ffn(d_model, dim_feedforward,
                       dropout if act_dropout is None else act_dropout,
                       activation, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.norm3 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        self_cache = cross_cache = None

        def self_attention(x):
            nonlocal self_cache
            if cache is None:
                return self.self_attn(x, x, x, tgt_mask)
            y, self_cache = self.self_attn(x, x, x, tgt_mask, cache[0])
            return y

        def cross_attention(x):
            nonlocal cross_cache
            if cache is None:
                return self.cross_attn(x, memory, memory, memory_mask)
            y, cross_cache = self.cross_attn(x, memory, memory,
                                             memory_mask, cache[1])
            return y

        pre = self.normalize_before
        tgt = _residual(tgt, self_attention, self.norm1, self.dropout1, pre)
        tgt = _residual(tgt, cross_attention, self.norm2, self.dropout2, pre)
        tgt = _residual(tgt, self._ffn, self.norm3, self.dropout3, pre)
        return tgt if cache is None else (tgt, (self_cache, cross_cache))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),
                self.cross_attn.gen_cache(
                    memory, memory, type=MultiHeadAttention.StaticCache))


def _clone_stack(layer, n):
    """n copies of `layer` (the given instance is copy 0, like the
    reference: the prototype joins the stack rather than being a dead
    template)."""
    return LayerList([layer] + [copy.deepcopy(layer) for _ in range(n - 1)])


class _LayerStack(Layer):
    """Shared encoder/decoder chassis: run the cloned layers in order,
    threading per-layer caches when decoding, then the optional final
    norm."""

    def __init__(self, layer, num_layers, norm=None):
        super().__init__()
        self.layers = _clone_stack(layer, num_layers)
        self.num_layers = num_layers
        self.norm = norm

    def _run(self, x, per_layer_args, cache):
        updated = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                x = layer(x, *per_layer_args)
            else:
                x, c = layer(x, *per_layer_args, cache[i])
                updated.append(c)
        if self.norm is not None:
            x = self.norm(x)
        return x if cache is None else (x, updated)


class TransformerEncoder(_LayerStack):
    def forward(self, src, src_mask=None, cache=None):
        return self._run(src, (src_mask,), cache)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoder(_LayerStack):
    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        return self._run(tgt, (memory, tgt_mask, memory_mask), cache)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        return list(zip(*caches)) if do_zip else caches


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        common = (dim_feedforward, dropout, activation, attn_dropout,
                  act_dropout, normalize_before, weight_attr, bias_attr)
        if custom_encoder is None:
            custom_encoder = TransformerEncoder(
                TransformerEncoderLayer(d_model, nhead, *common),
                num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is None:
            custom_decoder = TransformerDecoder(
                TransformerDecoderLayer(d_model, nhead, *common),
                num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.encoder = custom_encoder
        self.decoder = custom_decoder
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np
        from ...framework.tensor import to_tensor
        strictly_upper = np.triu(np.ones((length, length), bool), 1)
        return to_tensor(np.where(strictly_upper, -np.inf,
                                  0.0).astype(np.float32))
