"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells).

Reference analog: python/paddle/nn/layer/rnn.py over the cuDNN-backed phi rnn
kernel. TPU-native: the whole multi-layer, (bi)directional recurrence is ONE
op whose body is lax.scan over time — XLA compiles it into a single fused
while-loop on device (no per-timestep host dispatch), and it is fully
differentiable through the tape like any other op.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import apply
from ...framework.tensor import Tensor
from ..layer import Layer
from .. import initializer as I
from ..parameter import Parameter


def _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "GRU":
        # paddle/cudnn gate order: reset, update, candidate
        xr, xz, xn = jnp.split(x @ w_ih.T + (b_ih if b_ih is not None else 0),
                               3, axis=-1)
        hr, hz, hn = jnp.split(h @ w_hh.T + (b_hh if b_hh is not None else 0),
                               3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        return h_new, None
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    h_new = act(gates)
    return h_new, None


def _rnn_forward(x, h0, c0, *weights, mode="LSTM", num_layers=1,
                 bidirect=False, time_major=False, has_bias=True,
                 dropout=0.0):
    """x: [B,T,I] (or [T,B,I] if time_major). h0/c0: [L*D, B, H]."""
    if time_major:
        x = jnp.swapaxes(x, 0, 1)
    ndir = 2 if bidirect else 1
    per = 4 if has_bias else 2
    outs_h, outs_c = [], []
    inp = x
    for layer in range(num_layers):
        layer_outs = []
        for d in range(ndir):
            idx = (layer * ndir + d) * per
            w_ih, w_hh = weights[idx], weights[idx + 1]
            b_ih = weights[idx + 2] if has_bias else None
            b_hh = weights[idx + 3] if has_bias else None
            h_init = h0[layer * ndir + d]
            c_init = c0[layer * ndir + d] if c0 is not None else None
            seq = inp if d == 0 else jnp.flip(inp, axis=1)

            def step(carry, xt):
                h, c = carry
                h_new, c_new = _cell_step(mode, xt, h, c, w_ih, w_hh, b_ih,
                                          b_hh)
                return (h_new, c_new), h_new

            (h_last, c_last), ys = jax.lax.scan(
                step, (h_init, c_init), jnp.swapaxes(seq, 0, 1))
            ys = jnp.swapaxes(ys, 0, 1)  # [B,T,H]
            if d == 1:
                ys = jnp.flip(ys, axis=1)
            layer_outs.append(ys)
            outs_h.append(h_last)
            if c_last is not None:
                outs_c.append(c_last)
        inp = jnp.concatenate(layer_outs, axis=-1) if ndir == 2 \
            else layer_outs[0]
    out = inp
    if time_major:
        out = jnp.swapaxes(out, 0, 1)
    h_n = jnp.stack(outs_h, axis=0)
    if mode == "LSTM":
        c_n = jnp.stack(outs_c, axis=0)
        return out, h_n, c_n
    return out, h_n


class RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        gates = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        std = 1.0 / math.sqrt(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(ndir):
                in_size = input_size if layer == 0 else hidden_size * ndir
                suffix = f"_l{layer}" + ("_reverse" if d == 1 else "")
                w_ih = self.create_parameter(
                    [gates * hidden_size, in_size], weight_ih_attr,
                    default_initializer=I.Uniform(-std, std))
                w_hh = self.create_parameter(
                    [gates * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=I.Uniform(-std, std))
                b_ih = self.create_parameter(
                    [gates * hidden_size], bias_ih_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std))
                b_hh = self.create_parameter(
                    [gates * hidden_size], bias_hh_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std))
                self.add_parameter(f"weight_ih{suffix}", w_ih)
                self.add_parameter(f"weight_hh{suffix}", w_hh)
                self.add_parameter(f"bias_ih{suffix}", b_ih)
                self.add_parameter(f"bias_hh{suffix}", b_hh)
                self._all_weights += [w_ih, w_hh, b_ih, b_hh]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.creation import zeros
        ndir = 2 if self.bidirect else 1
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]
        if self.mode == "LSTM":
            if initial_states is None:
                h0 = zeros([self.num_layers * ndir, b, self.hidden_size],
                           inputs.dtype)
                c0 = zeros([self.num_layers * ndir, b, self.hidden_size],
                           inputs.dtype)
            else:
                h0, c0 = initial_states
            out, h_n, c_n = apply(
                f"rnn_{self.mode}", _rnn_forward, inputs, h0, c0,
                *self._all_weights, mode=self.mode,
                num_layers=self.num_layers, bidirect=self.bidirect,
                time_major=self.time_major, has_bias=True,
                dropout=self.dropout)
            return out, (h_n, c_n)
        if initial_states is None:
            h0 = zeros([self.num_layers * ndir, b, self.hidden_size],
                       inputs.dtype)
        else:
            h0 = initial_states
        out, h_n = apply(
            f"rnn_{self.mode}", _rnn_forward, inputs, h0, None,
            *self._all_weights, mode=self.mode, num_layers=self.num_layers,
            bidirect=self.bidirect, time_major=self.time_major,
            has_bias=True, dropout=self.dropout)
        return out, h_n


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class _CellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        b = batch_ref.shape[batch_dim_idx]
        return full([b, self.hidden_size], init_value,
                    dtype or batch_ref.dtype)


class LSTMCell(_CellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ...ops.creation import zeros
        if states is None:
            b = inputs.shape[0]
            states = (zeros([b, self.hidden_size], inputs.dtype),
                      zeros([b, self.hidden_size], inputs.dtype))
        h, c = states

        def _step(x, h, c, w_ih, w_hh, b_ih, b_hh):
            return _cell_step("LSTM", x, h, c, w_ih, w_hh, b_ih, b_hh)
        h_new, c_new = apply("lstm_cell", _step, inputs, h, c,
                             self.weight_ih, self.weight_hh, self.bias_ih,
                             self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(_CellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ...ops.creation import zeros
        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)

        def _step(x, h, w_ih, w_hh, b_ih, b_hh):
            h_new, _ = _cell_step("GRU", x, h, None, w_ih, w_hh, b_ih, b_hh)
            return h_new
        h_new = apply("gru_cell", _step, inputs, states, self.weight_ih,
                      self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, h_new


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ...ops.creation import zeros
        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        mode = self.mode

        def _step(x, h, w_ih, w_hh, b_ih, b_hh, mode=None):
            h_new, _ = _cell_step(mode, x, h, None, w_ih, w_hh, b_ih, b_hh)
            return h_new
        h_new = apply("rnn_cell", _step, inputs, states, self.weight_ih,
                      self.weight_hh, self.bias_ih, self.bias_hh, mode=mode)
        return h_new, h_new


class RNN(Layer):
    """Wrap a cell into a scan over time (reference: nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # host-level loop over leading time axis; jit captures it unrolled —
        # for long sequences use nn.LSTM/GRU (scan-based) instead
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        from ...ops.manipulation import stack
        for t in rng:
            xt = inputs[:, t] if axis == 1 else inputs[t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        if initial_states is None:
            fw_states = bw_states = None
        else:
            fw_states, bw_states = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
