"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""
from .layer import Layer  # noqa: F401
from .parameter import Parameter  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layers.common import (  # noqa: F401
    Linear, Identity, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, Bilinear,
    PixelShuffle, PixelUnshuffle, ChannelShuffle, Pad1D, Pad2D, Pad3D,
    ZeroPad2D, CosineSimilarity, PairwiseDistance, Unfold, Fold)
from .layers.container import (  # noqa: F401
    Sequential, LayerList, LayerDict, ParameterList)
from .layers.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose)
from .layers.norm import (  # noqa: F401
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm)
from .layers.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Mish, Softsign, Tanhshrink, LogSigmoid,
    Hardswish, Swish, GELU, LeakyReLU, ELU, CELU, SELU, PReLU, RReLU,
    Hardshrink, Softshrink, Hardtanh, Hardsigmoid, Softplus, ThresholdedReLU,
    Softmax, LogSoftmax, Maxout, GLU)
from .layers.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D)
from .layers.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, HuberLoss, MarginRankingLoss,
    HingeEmbeddingLoss, CosineEmbeddingLoss, TripletMarginLoss, CTCLoss)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer)
from .layers.rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, LSTMCell, GRUCell, SimpleRNNCell, RNN, BiRNN)
from . import utils  # noqa: F401
from .clip import ClipGradByNorm, ClipGradByValue, ClipGradByGlobalNorm  # noqa: F401
from .layers.rnn import _CellBase as RNNCellBase  # noqa: F401
from .layers.extra import (  # noqa: F401
    PoissonNLLLoss, SoftMarginLoss, MultiLabelSoftMarginLoss,
    MultiMarginLoss, TripletMarginWithDistanceLoss, GaussianNLLLoss,
    HSigmoidLoss, RNNTLoss, AdaptiveMaxPool3D, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D, Softmax2D, Unflatten)
from .decode import (  # noqa: F401
    Decoder, BeamSearchDecoder, dynamic_decode)
