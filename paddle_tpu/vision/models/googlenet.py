"""GoogLeNet / Inception v1 (reference
python/paddle/vision/models/googlenet.py — inception modules whose four
branches concat then ReLU ONCE, padding-0 max pools, and two auxiliary
heads off ince4a/ince4d; forward returns [out, out1, out2]). Mirrored
block-for-block: linear convs (no per-conv activation), AvgPool2D(5,3)
aux pooling (1152-wide flatten at 224 input), ReLU on aux1's fc only."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._utils import check_pretrained


def _conv(in_ch, out_ch, k, stride=1):
    """Reference ConvLayer: conv only, no activation."""
    return nn.Conv2D(in_ch, out_ch, k, stride, (k - 1) // 2,
                     bias_attr=False)


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.conv1 = _conv(in_ch, c1, 1)
        self.conv3r = _conv(in_ch, c3r, 1)
        self.conv3 = _conv(c3r, c3, 3)
        self.conv5r = _conv(in_ch, c5r, 1)
        self.conv5 = _conv(c5r, c5, 5)
        self.pool = nn.MaxPool2D(kernel_size=3, stride=1, padding=1)
        self.convprj = _conv(in_ch, proj, 1)
        self.relu = nn.ReLU()

    def forward(self, x):
        cat = paddle.concat(
            [self.conv1(x), self.conv3(self.conv3r(x)),
             self.conv5(self.conv5r(x)), self.convprj(self.pool(x))],
            axis=1)
        return self.relu(cat)              # one ReLU after the concat


class GoogLeNet(nn.Layer):
    """Reference GoogLeNet(num_classes, with_pool): forward returns
    [out, out1, out2] (aux heads off ince4a / ince4d)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv = _conv(3, 64, 7, 2)
        self.pool = nn.MaxPool2D(kernel_size=3, stride=2)  # padding=0
        self.conv_1 = _conv(64, 64, 1)
        self.conv_2 = _conv(64, 192, 3)
        self.ince3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.ince4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.ince5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool_5 = nn.AdaptiveAvgPool2D(1)
            self.pool_o1 = nn.AvgPool2D(kernel_size=5, stride=3)
            self.pool_o2 = nn.AvgPool2D(kernel_size=5, stride=3)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4, mode="downscale_in_infer")
            self.fc_out = nn.Linear(1024, num_classes)
            self.conv_o1 = _conv(512, 128, 1)
            self.fc_o1 = nn.Linear(1152, 1024)
            self.relu_o1 = nn.ReLU()
            self.drop_o1 = nn.Dropout(0.7, mode="downscale_in_infer")
            self.out1 = nn.Linear(1024, num_classes)
            self.conv_o2 = _conv(528, 128, 1)
            self.fc_o2 = nn.Linear(1152, 1024)
            self.drop_o2 = nn.Dropout(0.7, mode="downscale_in_infer")
            self.out2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool(self.conv(x))
        x = self.pool(self.conv_2(self.conv_1(x)))
        x = self.pool(self.ince3b(self.ince3a(x)))
        ince4a = self.ince4a(x)
        x = self.ince4c(self.ince4b(ince4a))
        ince4d = self.ince4d(x)
        x = self.pool(self.ince4e(ince4d))
        ince5b = self.ince5b(self.ince5a(x))

        out, out1, out2 = ince5b, ince4a, ince4d
        if self.with_pool:
            out = self.pool_5(out)
            out1 = self.pool_o1(out1)
            out2 = self.pool_o2(out2)
        if self.num_classes > 0:
            out = self.fc_out(paddle.squeeze(self.drop(out),
                                             axis=[2, 3]))
            out1 = self.fc_o1(self.conv_o1(out1).flatten(1))
            out1 = self.out1(self.drop_o1(self.relu_o1(out1)))
            # reference applies no relu on the second aux head
            out2 = self.fc_o2(self.conv_o2(out2).flatten(1))
            out2 = self.out2(self.drop_o2(out2))
        return [out, out1, out2]


def googlenet(pretrained=False, **kwargs):
    check_pretrained(pretrained)
    return GoogLeNet(**kwargs)
