"""SqueezeNet (reference python/paddle/vision/models/squeezenet.py —
fire modules: squeeze 1x1 then expand 1x1 + 3x3 concatenated)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._utils import check_pretrained


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return paddle.concat([self.relu(self.expand1(s)),
                              self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """Reference SqueezeNet(version '1.0'/'1.1', num_classes)."""

    def __init__(self, version="1.1", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(512, 64, 256, 256))
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(kernel_size=3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        else:
            raise ValueError(f"unknown SqueezeNet version {version!r}")
        # reference gating: num_classes>0 adds dropout+1x1-conv head;
        # with_pool independently adds relu+avgpool+squeeze
        if num_classes > 0:
            self.drop = nn.Dropout(0.5)
            self.conv9 = nn.Conv2D(512, num_classes, 1)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        x = self.features(x)
        if self.num_classes > 0:
            x = self.conv9(self.drop(x))
        if self.with_pool:
            x = self.avgpool(F.relu(x))
            x = paddle.squeeze(x, axis=[2, 3])
        return x


def squeezenet1_0(pretrained=False, **kw):
    check_pretrained(pretrained)
    return SqueezeNet(version="1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    check_pretrained(pretrained)
    return SqueezeNet(version="1.1", **kw)
