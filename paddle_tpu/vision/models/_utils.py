"""Shared helpers for the vision model factories."""
from __future__ import annotations


def check_pretrained(pretrained: bool) -> None:
    """All factories share one pretrained story: weights were an external
    download in the reference; here load a state_dict explicitly."""
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are an external download in the "
            "reference; load a state_dict via set_state_dict instead")


def conv_bn_act(in_ch, out_ch, k, stride=1, groups=1, act_layer=None):
    """The family-shared Conv2D(bias-free, same-pad) + BatchNorm2D (+
    activation instance) builder."""
    import paddle_tpu.nn as nn
    layers = [nn.Conv2D(in_ch, out_ch, k, stride, (k - 1) // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act_layer is not None:
        layers.append(act_layer)
    return nn.Sequential(*layers)
