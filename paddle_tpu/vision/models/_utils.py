"""Shared helpers for the vision model factories."""
from __future__ import annotations


def check_pretrained(pretrained: bool) -> None:
    """All factories share one pretrained story: weights were an external
    download in the reference; here load a state_dict explicitly."""
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are an external download in the "
            "reference; load a state_dict via set_state_dict instead")
