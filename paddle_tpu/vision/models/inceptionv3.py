"""Inception v3 (reference python/paddle/vision/models/inceptionv3.py:471 —
stem of five conv-bn-relu layers with two 3x3/2 max pools, then the
A(x3)/B/C(x4)/D/E(x2) block ladder from layers_config, adaptive avg pool
and a 2048-wide fc; every conv is Conv-BN-ReLU with bias-free convs).

Blocks mirror the reference channel plan: A(in, pool_features) =
[64 | 48>64(5x5) | 64>96>96(3x3 dbl) | avgpool>pool_features];
B(in) = strided reduction [384(3x3/2) | 64>96>96(3x3 dbl,/2) | maxpool/2];
C(in, c7) = factorized 7x7 [192 | c7>(1,7)>(7,1)192 | five-step dbl | 192];
D(in) = strided [192>320(3x3/2) | 192>(1,7)>(7,1)>192(3x3/2) | maxpool/2];
E(in) = split 3x3 [320 | 384>{(1,3),(3,1)} | 448>384>{(1,3),(3,1)} | 192].
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._utils import check_pretrained


class _CBR(nn.Sequential):
    """ConvNormActivation analog: bias-free conv + BN + ReLU."""

    def __init__(self, in_ch, out_ch, kernel_size, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_ch, out_ch, kernel_size, stride, padding,
                      bias_attr=False),
            nn.BatchNorm2D(out_ch),
            nn.ReLU())


def _avgpool3():
    # reference pools with exclusive=False (count_include_pad)
    return nn.AvgPool2D(kernel_size=3, stride=1, padding=1, exclusive=False)


class InceptionStem(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv_1a_3x3 = _CBR(3, 32, 3, stride=2)
        self.conv_2a_3x3 = _CBR(32, 32, 3)
        self.conv_2b_3x3 = _CBR(32, 64, 3, padding=1)
        self.max_pool = nn.MaxPool2D(kernel_size=3, stride=2)
        self.conv_3b_1x1 = _CBR(64, 80, 1)
        self.conv_4a_3x3 = _CBR(80, 192, 3)

    def forward(self, x):
        x = self.conv_2b_3x3(self.conv_2a_3x3(self.conv_1a_3x3(x)))
        x = self.conv_4a_3x3(self.conv_3b_1x1(self.max_pool(x)))
        return self.max_pool(x)


class InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_features):
        super().__init__()
        self.branch1x1 = _CBR(in_ch, 64, 1)
        self.branch5x5 = nn.Sequential(_CBR(in_ch, 48, 1),
                                       _CBR(48, 64, 5, padding=2))
        self.branch3x3dbl = nn.Sequential(_CBR(in_ch, 64, 1),
                                          _CBR(64, 96, 3, padding=1),
                                          _CBR(96, 96, 3, padding=1))
        self.branch_pool = nn.Sequential(_avgpool3(),
                                         _CBR(in_ch, pool_features, 1))

    def forward(self, x):
        return paddle.concat(
            [self.branch1x1(x), self.branch5x5(x), self.branch3x3dbl(x),
             self.branch_pool(x)], axis=1)


class InceptionB(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.branch3x3 = _CBR(in_ch, 384, 3, stride=2)
        self.branch3x3dbl = nn.Sequential(_CBR(in_ch, 64, 1),
                                          _CBR(64, 96, 3, padding=1),
                                          _CBR(96, 96, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return paddle.concat(
            [self.branch3x3(x), self.branch3x3dbl(x), self.branch_pool(x)],
            axis=1)


class InceptionC(nn.Layer):
    def __init__(self, in_ch, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = _CBR(in_ch, 192, 1)
        self.branch7x7 = nn.Sequential(
            _CBR(in_ch, c7, 1),
            _CBR(c7, c7, (1, 7), padding=(0, 3)),
            _CBR(c7, 192, (7, 1), padding=(3, 0)))
        self.branch7x7dbl = nn.Sequential(
            _CBR(in_ch, c7, 1),
            _CBR(c7, c7, (7, 1), padding=(3, 0)),
            _CBR(c7, c7, (1, 7), padding=(0, 3)),
            _CBR(c7, c7, (7, 1), padding=(3, 0)),
            _CBR(c7, 192, (1, 7), padding=(0, 3)))
        self.branch_pool = nn.Sequential(_avgpool3(), _CBR(in_ch, 192, 1))

    def forward(self, x):
        return paddle.concat(
            [self.branch1x1(x), self.branch7x7(x), self.branch7x7dbl(x),
             self.branch_pool(x)], axis=1)


class InceptionD(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.branch3x3 = nn.Sequential(_CBR(in_ch, 192, 1),
                                       _CBR(192, 320, 3, stride=2))
        self.branch7x7x3 = nn.Sequential(
            _CBR(in_ch, 192, 1),
            _CBR(192, 192, (1, 7), padding=(0, 3)),
            _CBR(192, 192, (7, 1), padding=(3, 0)),
            _CBR(192, 192, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return paddle.concat(
            [self.branch3x3(x), self.branch7x7x3(x), self.branch_pool(x)],
            axis=1)


class InceptionE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.branch1x1 = _CBR(in_ch, 320, 1)
        self.branch3x3_1 = _CBR(in_ch, 384, 1)
        self.branch3x3_2a = _CBR(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = _CBR(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = nn.Sequential(_CBR(in_ch, 448, 1),
                                            _CBR(448, 384, 3, padding=1))
        self.branch3x3dbl_3a = _CBR(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = _CBR(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = nn.Sequential(_avgpool3(), _CBR(in_ch, 192, 1))

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = paddle.concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)],
                           axis=1)
        bd = self.branch3x3dbl_1(x)
        bd = paddle.concat([self.branch3x3dbl_3a(bd),
                            self.branch3x3dbl_3b(bd)], axis=1)
        return paddle.concat(
            [self.branch1x1(x), b3, bd, self.branch_pool(x)], axis=1)


class InceptionV3(nn.Layer):
    """Reference InceptionV3(num_classes, with_pool); input 299x299,
    output [N, num_classes] (no aux head in the reference port)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inception_stem = InceptionStem()
        blocks = [InceptionA(192, 32), InceptionA(256, 64),
                  InceptionA(288, 64),
                  InceptionB(288),
                  InceptionC(768, 128), InceptionC(768, 160),
                  InceptionC(768, 160), InceptionC(768, 192),
                  InceptionD(768),
                  InceptionE(1280), InceptionE(2048)]
        self.inception_block_list = nn.LayerList(blocks)
        if with_pool:
            self.avg_pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            # reference uses downscale_in_infer: eval scales by (1-p)
            self.dropout = nn.Dropout(p=0.2, mode="downscale_in_infer")
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.inception_stem(x)
        for block in self.inception_block_list:
            x = block(x)
        if self.with_pool:
            x = self.avg_pool(x)
        if self.num_classes > 0:
            x = paddle.reshape(x, [-1, 2048])
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    check_pretrained(pretrained)
    return InceptionV3(**kwargs)
