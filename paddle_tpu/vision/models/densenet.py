"""DenseNet (reference python/paddle/vision/models/densenet.py —
dense blocks with concatenated features + transition downsampling)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._utils import check_pretrained

_CFGS = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout=0.0):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv1(self.relu(self.norm1(x)))
        y = self.conv2(self.relu(self.norm2(y)))
        if self.dropout is not None:
            y = self.dropout(y)
        return paddle.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    """Reference DenseNet(layers, bn_size, dropout, num_classes)."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_ch, growth, block_cfg = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(kernel_size=3, stride=2, padding=1)]
        ch = init_ch
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kw):
    check_pretrained(pretrained)
    return DenseNet(layers=layers, **kw)


def densenet121(pretrained=False, **kw):
    return _densenet(121, pretrained, **kw)


def densenet161(pretrained=False, **kw):
    return _densenet(161, pretrained, **kw)


def densenet169(pretrained=False, **kw):
    return _densenet(169, pretrained, **kw)


def densenet201(pretrained=False, **kw):
    return _densenet(201, pretrained, **kw)


def densenet264(pretrained=False, **kw):
    return _densenet(264, pretrained, **kw)
