"""ShuffleNetV2 (reference python/paddle/vision/models/shufflenetv2.py —
channel-split units with channel shuffle, depthwise 3x3)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._utils import check_pretrained

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    # NB: the reference's x2.0 table (shufflenetv2.py:241) uses 224, not
    # the paper's 244 — mirror the reference
    2.0: [24, 224, 488, 976, 2048],
}
_REPEATS = [4, 8, 4]


def _channel_shuffle(x, groups=2):
    B, C, H, W = x.shape
    x = paddle.reshape(x, [B, groups, C // groups, H, W])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [B, C, H, W])


def _act_layer(act):
    """Reference create_activation_layer: relu / swish, reject others."""
    if act == "relu":
        return nn.ReLU()
    if act == "swish":
        return nn.Swish()
    raise ValueError(f"unsupported activation {act!r} (relu|swish)")


def _conv_bn(in_ch, out_ch, k, stride=1, groups=1, act="relu"):
    layers = [nn.Conv2D(in_ch, out_ch, k, stride, (k - 1) // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act is not None:
        layers.append(_act_layer(act))
    return nn.Sequential(*layers)


class _Unit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(in_ch // 2, branch_ch, 1, act=act),
                _conv_bn(branch_ch, branch_ch, 3, groups=branch_ch,
                         act=None),
                _conv_bn(branch_ch, branch_ch, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(in_ch, in_ch, 3, stride, groups=in_ch,
                         act=None),
                _conv_bn(in_ch, branch_ch, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn(in_ch, branch_ch, 1, act=act),
                _conv_bn(branch_ch, branch_ch, 3, stride,
                         groups=branch_ch, act=None),
                _conv_bn(branch_ch, branch_ch, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1 = x[:, :c]
            x2 = x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)],
                                axis=1)
        return _channel_shuffle(out)


class ShuffleNetV2(nn.Layer):
    """Reference ShuffleNetV2(scale, num_classes, with_pool)."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"unsupported scale {scale}")
        outs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn(3, outs[0], 3, stride=2, act=act)
        self.pool1 = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        stages = []
        in_ch = outs[0]
        for stage_i, reps in enumerate(_REPEATS):
            out_ch = outs[stage_i + 1]
            stages.append(_Unit(in_ch, out_ch, stride=2, act=act))
            for _ in range(reps - 1):
                stages.append(_Unit(out_ch, out_ch, stride=1, act=act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(in_ch, outs[-1], 1, act=act)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def shufflenet_v2_x1_0(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x0_25(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    """Reference shufflenet_v2_swish: scale=1.0 with swish activations."""
    check_pretrained(pretrained)
    return ShuffleNetV2(scale=1.0, act="swish", **kw)
