"""MobileNetV3 (reference python/paddle/vision/models/mobilenetv3.py —
inverted residuals with squeeze-excitation and hardswish, small/large
configs)."""
from __future__ import annotations

import paddle_tpu.nn as nn

from ._utils import check_pretrained, conv_bn_act
from .mobilenetv2 import _make_divisible


# (kernel, expanded, out, use_se, activation, stride) per block
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


def _act(name):
    return nn.Hardswish() if name == "hardswish" else nn.ReLU()


def _conv_bn_act(in_ch, out_ch, k, stride=1, groups=1, act="hardswish"):
    return conv_bn_act(in_ch, out_ch, k, stride, groups,
                       act_layer=None if act is None else _act(act))


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        mid = _make_divisible(ch // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, k, exp, out_ch, use_se, act, stride):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp != in_ch:
            layers.append(_conv_bn_act(in_ch, exp, 1, act=act))
        layers.append(_conv_bn_act(exp, exp, k, stride, groups=exp,
                                   act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp))
        layers.append(_conv_bn_act(exp, out_ch, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        in_ch = c(16)
        layers = [_conv_bn_act(3, in_ch, 3, stride=2, act="hardswish")]
        for k, exp, out, use_se, act, stride in cfg:
            layers.append(_InvertedResidual(
                in_ch, k, c(exp), c(out), use_se, act, stride))
            in_ch = c(out)
        # reference: lastconv_output_channels = 6 * adjusted last out
        last_conv = 6 * c(cfg[-1][2])
        layers.append(_conv_bn_act(in_ch, last_conv, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(MobileNetV3):
    """Reference MobileNetV3Large(scale, num_classes, with_pool)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    """Reference MobileNetV3Small(scale, num_classes, with_pool)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    check_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    check_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kw)
