"""MobileNetV1 (reference python/paddle/vision/models/mobilenetv1.py —
depthwise-separable conv stacks with width multiplier)."""
from __future__ import annotations

import paddle_tpu.nn as nn

from ._utils import check_pretrained, conv_bn_act


def _conv_bn(in_ch, out_ch, k, stride=1, groups=1):
    return conv_bn_act(in_ch, out_ch, k, stride, groups,
                       act_layer=nn.ReLU())


def _depthwise_separable(in_ch, out_ch, stride):
    return nn.Sequential(
        _conv_bn(in_ch, in_ch, 3, stride, groups=in_ch),
        _conv_bn(in_ch, out_ch, 1))


class MobileNetV1(nn.Layer):
    """Reference MobileNetV1(scale, num_classes, with_pool)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            # exact reference channel math (int(ch*scale), no floor) so
            # reference state_dicts load shape-for-shape at any scale
            return int(ch * scale)

        cfg = [  # (out_ch, stride)
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        layers = [_conv_bn(3, c(32), 3, stride=2)]
        in_ch = c(32)
        for out_ch, stride in cfg:
            layers.append(_depthwise_separable(in_ch, c(out_ch), stride))
            in_ch = c(out_ch)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    check_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)
