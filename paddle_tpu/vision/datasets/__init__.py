"""Vision datasets (reference python/paddle/vision/datasets/ — MNIST,
Cifar10 etc. download external archives; no egress here, so the classes
read LOCAL files in the original formats, and FakeData provides the
synthetic path the benches use)."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "DatasetFolder", "ImageFolder", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data (the bench/test
    fixture — reference tests use the same trick via numpy fixtures)."""

    def __init__(self, num_samples=1024, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.randn(
            min(num_samples, 64), *self.image_shape).astype(np.float32)
        self._labels = self._rng.randint(
            0, num_classes, num_samples).astype(np.int64)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        img = self._images[idx % len(self._images)]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]


class MNIST(Dataset):
    """Reads the original IDX files from `image_path`/`label_path`
    (reference datasets/mnist.py minus the downloader)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise NotImplementedError(
                "MNIST download needs network egress; pass image_path/"
                "label_path to local IDX files (train-images-idx3-ubyte.gz"
                " / train-labels-idx1-ubyte.gz)")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _load(image_path, label_path):
        opener = gzip.open if str(image_path).endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        opener = gzip.open if str(label_path).endswith(".gz") else open
        with opener(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    """Same IDX format as MNIST (reference datasets/fashion_mnist)."""


class Cifar10(Dataset):
    """Reads the original python-pickle batches from a local
    cifar-10-python.tar.gz (reference datasets/cifar.py minus the
    downloader). Cifar100 differs only in the member names (class
    attribute _MEMBERS) — label lookup already covers both via the
    reference's labels->fine_labels fallback (cifar.py:166)."""

    _NAME = "Cifar10"
    _MEMBERS = {"train": [f"data_batch_{i}" for i in range(1, 6)],
                "test": ["test_batch"]}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise NotImplementedError(
                f"{self._NAME} download needs network egress; pass "
                f"data_file pointing at the local python-version tar.gz")
        self.transform = transform
        names = self._MEMBERS["train" if mode == "train" else "test"]
        xs, ys = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for m in tf.getmembers():
                if os.path.basename(m.name) in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"]))
                    ys.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    """Reference datasets/cifar.py Cifar100: same pickle format, members
    named train/test inside cifar-100-python.tar.gz, fine_labels."""

    _NAME = "Cifar100"
    _MEMBERS = {"train": ["train"], "test": ["test"]}


class _TarReader:
    """Per-process lazy tar handle: forked DataLoader workers would
    otherwise share one fd (and its seek offset) with the parent, racing
    extractfile reads across processes. Each process reopens on first
    use."""

    def __init__(self, path):
        self._path = path
        self._pid = None
        self._tar = None

    def read(self, name):
        if self._tar is None or self._pid != os.getpid():
            self._tar = tarfile.open(self._path)
            self._pid = os.getpid()
        return self._tar.extractfile(name).read()

    def close(self):
        if self._tar is not None and self._pid == os.getpid():
            try:
                self._tar.close()
            except Exception:
                pass
        self._tar = None


class Flowers(Dataset):
    """Reference datasets/flowers.py: 102-category flowers; reads the
    local 102flowers tgz (jpg/image_%05d.jpg), imagelabels.mat and
    setid.mat. NB the reference's MODE_FLAG_MAP (flowers.py:38) maps
    train->tstid and test->trnid on purpose (the official test split is
    the larger one) — mirrored here."""

    _MODE_FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        if download and data_file is None:
            raise NotImplementedError(
                "Flowers download needs network egress; pass data_file/"
                "label_file/setid_file paths to the local archives")
        if mode.lower() not in self._MODE_FLAG:
            raise AssertionError(
                f"mode should be 'train', 'valid' or 'test', got {mode}")
        import scipy.io as scio
        self.transform = transform
        self.indexes = scio.loadmat(setid_file)[
            self._MODE_FLAG[mode.lower()]][0]
        self.labels = scio.loadmat(label_file)["labels"][0]
        self._tar = _TarReader(data_file)

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]])
        raw = self._tar.read("jpg/image_%05d.jpg" % index)
        img = np.array(Image.open(_io.BytesIO(raw)))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __del__(self):
        try:
            self._tar.close()
        except Exception:
            pass


class VOC2012(Dataset):
    """Reference datasets/voc2012.py: segmentation pairs out of the local
    VOCtrainval tar (ImageSets/Segmentation/{mode}.txt ->
    JPEGImages/*.jpg + SegmentationClass/*.png)."""

    # archive-internal layout of the VOCtrainval tarball
    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _DATA = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LABEL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise NotImplementedError(
                "VOC2012 download needs network egress; pass data_file "
                "pointing at the local VOCtrainval tar")
        if mode.lower() not in ("train", "valid", "test"):
            raise AssertionError(
                f"mode should be 'train', 'valid' or 'test', got {mode}")
        # reference MODE_FLAG_MAP (voc2012.py:36): train reads the larger
        # trainval split, test reads train
        flag = {"train": "trainval", "valid": "val",
                "test": "train"}[mode.lower()]
        self.transform = transform
        self._tar = _TarReader(data_file)
        names = self._tar.read(self._SET.format(flag)).split()
        self.data = [self._DATA.format(n.decode()) for n in names]
        self.labels = [self._LABEL.format(n.decode()) for n in names]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        img = np.array(Image.open(_io.BytesIO(
            self._tar.read(self.data[idx]))))
        label = np.array(Image.open(_io.BytesIO(
            self._tar.read(self.labels[idx]))))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __del__(self):
        try:
            self._tar.close()
        except Exception:
            pass


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return np.array(Image.open(f).convert("RGB"))


class DatasetFolder(Dataset):
    """Reference datasets/folder.py DatasetFolder: root/class_x/xxx.ext
    layout -> (sample, class_index); classes sorted alphabetically."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "both extensions and is_valid_file cannot be passed")
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        self.classes = sorted(
            d.name for d in os.scandir(root) if d.is_dir())
        if not self.classes:
            raise RuntimeError(f"found 0 class directories in {root}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for dirpath, _dirs, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    p = os.path.join(dirpath, fname)
                    if is_valid_file(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"found 0 files in subfolders of {root} "
                f"(supported extensions: {extensions})")
        self.targets = [t for _p, t in self.samples]

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """Reference datasets/folder.py ImageFolder: flat/recursive image
    list, returns [sample] (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "both extensions and is_valid_file cannot be passed")
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        self.samples = []
        for dirpath, _dirs, files in sorted(os.walk(root)):
            for fname in sorted(files):
                p = os.path.join(dirpath, fname)
                if is_valid_file(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"found 0 files in {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]
