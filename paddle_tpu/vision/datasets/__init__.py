"""Vision datasets (reference python/paddle/vision/datasets/ — MNIST,
Cifar10 etc. download external archives; no egress here, so the classes
read LOCAL files in the original formats, and FakeData provides the
synthetic path the benches use)."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data (the bench/test
    fixture — reference tests use the same trick via numpy fixtures)."""

    def __init__(self, num_samples=1024, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.randn(
            min(num_samples, 64), *self.image_shape).astype(np.float32)
        self._labels = self._rng.randint(
            0, num_classes, num_samples).astype(np.int64)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        img = self._images[idx % len(self._images)]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]


class MNIST(Dataset):
    """Reads the original IDX files from `image_path`/`label_path`
    (reference datasets/mnist.py minus the downloader)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise NotImplementedError(
                "MNIST download needs network egress; pass image_path/"
                "label_path to local IDX files (train-images-idx3-ubyte.gz"
                " / train-labels-idx1-ubyte.gz)")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _load(image_path, label_path):
        opener = gzip.open if str(image_path).endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        opener = gzip.open if str(label_path).endswith(".gz") else open
        with opener(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    """Same IDX format as MNIST (reference datasets/fashion_mnist)."""


class Cifar10(Dataset):
    """Reads the original python-pickle batches from a local
    cifar-10-python.tar.gz (reference datasets/cifar.py minus the
    downloader)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise NotImplementedError(
                "Cifar10 download needs network egress; pass data_file "
                "pointing at a local cifar-10-python.tar.gz")
        self.transform = transform
        names = [f"data_batch_{i}" for i in range(1, 6)] \
            if mode == "train" else ["test_batch"]
        xs, ys = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"]))
                    ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]
