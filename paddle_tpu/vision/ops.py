"""Vision ops: nms, roi_align (reference python/paddle/vision/ops.py over
phi nms/roi_align kernels — the two vision ops the op-coverage ledger
tracks; the wider detection zoo is descoped there with reasons).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.dispatch import apply
from ..framework.tensor import Tensor, to_tensor

__all__ = ["nms", "roi_align", "box_iou"]


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for [x1,y1,x2,y2] boxes."""
    def _iou(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter,
                                   1e-10)
    return apply("box_iou", _iou, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference vision/ops.py:nms / phi nms_kernel). Returns
    kept indices sorted by descending score. TPU-shaped: a fixed-length
    lax.fori_loop over the score-sorted suppression mask (static shapes),
    with the final variable-length index extraction on host."""
    n = boxes.shape[0]
    bv = boxes._value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    if scores is None:
        order = jnp.arange(n)
        sv = None
    else:
        sv = scores._value if isinstance(scores, Tensor) \
            else jnp.asarray(scores)
        order = jnp.argsort(-sv)

    if category_idxs is not None:
        # per-category NMS: offset boxes per category so categories never
        # overlap (the standard batched-NMS trick)
        cv = (category_idxs._value if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs)).astype(bv.dtype)
        span = jnp.max(bv) - jnp.min(bv) + 1.0
        bv = bv + (cv * span)[:, None]

    keep = np.asarray(_nms_suppress(bv, order, float(iou_threshold)))
    kept = np.asarray(order)[keep]
    if top_k is not None:
        kept = kept[:top_k]
    return to_tensor(kept.astype(np.int64))


import functools


@functools.partial(jax.jit, static_argnames=("iou_threshold",))
def _nms_suppress(bv, order, iou_threshold):
    """Module-level jitted suppression loop: compiles once per (shape,
    threshold), not per nms() call."""
    n = bv.shape[0]
    b = bv[order]
    iou = _pairwise_iou(b)

    def body(i, keep):
        # suppress j>i overlapping with kept i
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup
    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def _pairwise_iou(b):
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area[:, None] + area[None] - inter, 1e-10)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ROI Align (reference vision/ops.py:roi_align / phi
    roi_align_kernel): x [N,C,H,W], boxes [R,4] (x1,y1,x2,y2),
    boxes_num [N] rois per image → [R, C, out_h, out_w].
    Bilinear-sampled grid per ROI — gathers + lerp, one fused XLA kernel."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out_h, out_w = output_size

    bn = (boxes_num.numpy() if isinstance(boxes_num, Tensor)
          else np.asarray(boxes_num)).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def _roi(x, boxes, bidx, out_h, out_w, scale, ratio, aligned):
        R = boxes.shape[0]
        N, C, H, W = x.shape
        off = 0.5 if aligned else 0.0
        x1 = boxes[:, 0] * scale - off
        y1 = boxes[:, 1] * scale - off
        x2 = boxes[:, 2] * scale - off
        y2 = boxes[:, 3] * scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        sr_h = ratio if ratio > 0 else 2
        sr_w = ratio if ratio > 0 else 2
        # sample points: [R, out_h*sr_h] y coords, [R, out_w*sr_w] x
        ys = (y1[:, None] + rh[:, None]
              * (jnp.arange(out_h * sr_h) + 0.5) / (out_h * sr_h))
        xs = (x1[:, None] + rw[:, None]
              * (jnp.arange(out_w * sr_w) + 0.5) / (out_w * sr_w))

        # bilinear sample one image at a [Sy, Sx] coordinate grid → [Sy,Sx,C]
        def bilinear(img, yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy1 = jnp.clip(yy - y0, 0, 1)
            wx1 = jnp.clip(xx - x0, 0, 1)
            out = 0.0
            for iy, wy in ((y0, 1 - wy1), (y1_, wy1)):
                for ix, wx in ((x0, 1 - wx1), (x1_, wx1)):
                    v = img[iy.astype(jnp.int32), ix.astype(jnp.int32)]
                    out = out + v * (wy * wx)[:, :, None]
            return out

        imgs = jnp.moveaxis(x, 1, -1)[bidx]          # [R, H, W, C]

        # vectorize over ROIs
        def sample_one(img, yy, xx):
            # yy [Sy], xx [Sx] -> grid [Sy, Sx, C]
            yg = jnp.broadcast_to(yy[:, None], (yy.shape[0], xx.shape[0]))
            xg = jnp.broadcast_to(xx[None, :], (yy.shape[0], xx.shape[0]))
            return bilinear(img, yg, xg)

        grids = jax.vmap(sample_one)(imgs, ys, xs)   # [R, Sy, Sx, C]
        # average pool each (sr_h, sr_w) cell -> [R, out_h, out_w, C]
        g = grids.reshape(R, out_h, sr_h, out_w, sr_w, C)
        pooled = jnp.mean(g, axis=(2, 4))
        return jnp.moveaxis(pooled, -1, 1)           # [R, C, out_h, out_w]

    return apply("roi_align", _roi, x, boxes, batch_idx, out_h=int(out_h),
                 out_w=int(out_w), scale=float(spatial_scale),
                 ratio=int(sampling_ratio), aligned=bool(aligned))
