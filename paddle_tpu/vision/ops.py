"""Vision ops (reference python/paddle/vision/ops.py over the phi
detection kernel zoo): nms/roi ops plus the detection pack — box_coder,
prior_box, yolo_box/yolo_loss, matrix_nms, FPN proposal ops,
deform_conv2d. The op-coverage ledger (ops/optable.py) aliases the
reference YAML ops onto these entry points.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.dispatch import apply
from ..framework.tensor import Tensor, to_tensor
from ..nn.layer import Layer

__all__ = [
    "box_iou", "nms", "roi_align", "roi_pool", "RoIPool", "RoIAlign",
    "psroi_pool", "PSRoIPool", "deform_conv2d", "DeformConv2D",
    "box_coder", "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
    "distribute_fpn_proposals", "generate_proposals", "read_file",
    "decode_jpeg",
]


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for [x1,y1,x2,y2] boxes."""
    def _iou(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter,
                                   1e-10)
    return apply("box_iou", _iou, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference vision/ops.py:nms / phi nms_kernel). Returns
    kept indices sorted by descending score. TPU-shaped: a fixed-length
    lax.fori_loop over the score-sorted suppression mask (static shapes),
    with the final variable-length index extraction on host."""
    n = boxes.shape[0]
    bv = boxes._value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    if scores is None:
        order = jnp.arange(n)
        sv = None
    else:
        sv = scores._value if isinstance(scores, Tensor) \
            else jnp.asarray(scores)
        order = jnp.argsort(-sv)

    if category_idxs is not None:
        # per-category NMS: offset boxes per category so categories never
        # overlap (the standard batched-NMS trick)
        cv = (category_idxs._value if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs)).astype(bv.dtype)
        span = jnp.max(bv) - jnp.min(bv) + 1.0
        bv = bv + (cv * span)[:, None]

    keep = np.asarray(_nms_suppress(bv, order, float(iou_threshold)))
    kept = np.asarray(order)[keep]
    if top_k is not None:
        kept = kept[:top_k]
    return to_tensor(kept.astype(np.int64))


import functools


@functools.partial(jax.jit, static_argnames=("iou_threshold",))
def _nms_suppress(bv, order, iou_threshold):
    """Module-level jitted suppression loop: compiles once per (shape,
    threshold), not per nms() call."""
    n = bv.shape[0]
    b = bv[order]
    iou = _pairwise_iou(b)

    def body(i, keep):
        # suppress j>i overlapping with kept i
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup
    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def _pairwise_iou(b):
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area[:, None] + area[None] - inter, 1e-10)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ROI Align (reference vision/ops.py:roi_align / phi
    roi_align_kernel): x [N,C,H,W], boxes [R,4] (x1,y1,x2,y2),
    boxes_num [N] rois per image → [R, C, out_h, out_w].
    Bilinear-sampled grid per ROI — gathers + lerp, one fused XLA kernel."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out_h, out_w = output_size

    bn = (boxes_num.numpy() if isinstance(boxes_num, Tensor)
          else np.asarray(boxes_num)).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def _roi(x, boxes, bidx, out_h, out_w, scale, ratio, aligned):
        R = boxes.shape[0]
        N, C, H, W = x.shape
        off = 0.5 if aligned else 0.0
        x1 = boxes[:, 0] * scale - off
        y1 = boxes[:, 1] * scale - off
        x2 = boxes[:, 2] * scale - off
        y2 = boxes[:, 3] * scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        sr_h = ratio if ratio > 0 else 2
        sr_w = ratio if ratio > 0 else 2
        # sample points: [R, out_h*sr_h] y coords, [R, out_w*sr_w] x
        ys = (y1[:, None] + rh[:, None]
              * (jnp.arange(out_h * sr_h) + 0.5) / (out_h * sr_h))
        xs = (x1[:, None] + rw[:, None]
              * (jnp.arange(out_w * sr_w) + 0.5) / (out_w * sr_w))

        # bilinear sample one image at a [Sy, Sx] coordinate grid → [Sy,Sx,C]
        def bilinear(img, yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy1 = jnp.clip(yy - y0, 0, 1)
            wx1 = jnp.clip(xx - x0, 0, 1)
            out = 0.0
            for iy, wy in ((y0, 1 - wy1), (y1_, wy1)):
                for ix, wx in ((x0, 1 - wx1), (x1_, wx1)):
                    v = img[iy.astype(jnp.int32), ix.astype(jnp.int32)]
                    out = out + v * (wy * wx)[:, :, None]
            return out

        imgs = jnp.moveaxis(x, 1, -1)[bidx]          # [R, H, W, C]

        # vectorize over ROIs
        def sample_one(img, yy, xx):
            # yy [Sy], xx [Sx] -> grid [Sy, Sx, C]
            yg = jnp.broadcast_to(yy[:, None], (yy.shape[0], xx.shape[0]))
            xg = jnp.broadcast_to(xx[None, :], (yy.shape[0], xx.shape[0]))
            return bilinear(img, yg, xg)

        grids = jax.vmap(sample_one)(imgs, ys, xs)   # [R, Sy, Sx, C]
        # average pool each (sr_h, sr_w) cell -> [R, out_h, out_w, C]
        g = grids.reshape(R, out_h, sr_h, out_w, sr_w, C)
        pooled = jnp.mean(g, axis=(2, 4))
        return jnp.moveaxis(pooled, -1, 1)           # [R, C, out_h, out_w]

    return apply("roi_align", _roi, x, boxes, batch_idx, out_h=int(out_h),
                 out_w=int(out_w), scale=float(spatial_scale),
                 ratio=int(sampling_ratio), aligned=bool(aligned))


# ======================================================================
# Detection-op pack (reference python/paddle/vision/ops.py:267 yolo_box,
# :428 prior_box, :574 box_coder, :700+ deform_conv2d/DeformConv2D,
# roi_pool/psroi_pool, distribute_fpn_proposals, generate_proposals,
# matrix_nms, read_file/decode_jpeg, and the yolo_loss training op).
# Box-space math is pure jnp (jit/grad-friendly); proposal selection
# with data-dependent counts runs top-k/padded — the TPU contract.
# ======================================================================

def read_file(filename, name=None):
    """reference ops.py read_file — raw bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """reference ops.py decode_jpeg — CHW uint8 (PIL backend here; the
    reference uses nvjpeg on GPU)."""
    import io as _io
    from PIL import Image
    buf = np.asarray(x._value if isinstance(x, Tensor) else x,
                     np.uint8).tobytes()
    img = Image.open(_io.BytesIO(buf))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "unchanged"):
        img = img.convert("RGB") if mode == "rgb" else img
    arr = np.array(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference ops.py deform_conv2d (DCNv1 when mask is None, DCNv2
    with mask): bilinear-sampled taps + MXU contraction — the functional
    core static.nn.deform_conv2d builds its params around."""
    from ..framework.dispatch import apply

    def _pair(v):
        return (v,) * 2 if isinstance(v, int) else tuple(v)

    kh, kw = weight.shape[2], weight.shape[3]
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    if deformable_groups != 1 or groups != 1:
        raise NotImplementedError(
            "deform_conv2d supports groups == deformable_groups == 1")

    def _dcn(xv, off, m, wv, bv, cfg=None):
        kh, kw, sh, sw, ph, pw, dh, dw = cfg
        B, C, H, W = xv.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        ys = jnp.arange(Ho) * sh - ph
        xs = jnp.arange(Wo) * sw - pw
        off = off.reshape(B, kh * kw, 2, Ho, Wo)
        dy, dx = off[:, :, 0], off[:, :, 1]
        ti = jnp.repeat(jnp.arange(kh), kw)
        tj = jnp.tile(jnp.arange(kw), kh)
        sy = (ys[None, None, :, None]
              + ti[None, :, None, None] * dh).astype(jnp.float32)
        sy = jnp.broadcast_to(sy, (B, kh * kw, Ho, Wo)) + dy
        sx = (xs[None, None, None, :]
              + tj[None, :, None, None] * dw).astype(jnp.float32)
        sx = jnp.broadcast_to(sx, (B, kh * kw, Ho, Wo)) + dx
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
            valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                     & (xx <= W - 1)).astype(xv.dtype)
            g = xv[jnp.arange(B)[:, None, None, None], :,
                   yi[:, :, :, :], xi[:, :, :, :]]
            g = jnp.moveaxis(g, -1, 1)
            return g * valid[:, None]

        val = (gather(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
               + gather(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
               + gather(y0 + 1, x0) * (wy * (1 - wx))[:, None]
               + gather(y0 + 1, x0 + 1) * (wy * wx)[:, None])
        if m is not None:
            val = val * m.reshape(B, 1, kh * kw, Ho, Wo)
        out = jnp.einsum("bckhw,fck->bfhw", val,
                         wv.reshape(wv.shape[0], C, kh * kw))
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    return apply("deform_conv2d_fn", _dcn, x, offset, mask, weight,
                 bias, cfg=(kh, kw, sh, sw, ph, pw, dh, dw))


class DeformConv2D(Layer):
    """reference ops.py DeformConv2D layer over deform_conv2d."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = (stride, padding, dilation, deformable_groups,
                     groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *ks), attr=weight_attr)
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """reference ops.py roi_pool — max pooling over ROI bins."""
    from ..framework.dispatch import apply
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi_pool(xv, bx, bnum, _oh=7, _ow=7, _scale=1.0):
        N = bx.shape[0]
        counts = jnp.cumsum(bnum)
        batch_idx = jnp.searchsorted(counts,
                                     jnp.arange(N), side="right")
        scaled = bx * _scale
        x1, y1, x2, y2 = (scaled[:, 0], scaled[:, 1], scaled[:, 2],
                          scaled[:, 3])
        H, W = xv.shape[2], xv.shape[3]

        def one_box(b, xx1, yy1, xx2, yy2):
            img = xv[b]                      # [C, H, W]
            ys = jnp.linspace(yy1, yy2, _oh + 1)
            xs = jnp.linspace(xx1, xx2, _ow + 1)
            pos_y = jnp.arange(H)[None, :]
            pos_x = jnp.arange(W)[None, :]
            rowm = (pos_y >= jnp.floor(ys[:-1, None])) & \
                (pos_y < jnp.maximum(jnp.ceil(ys[1:, None]),
                                     jnp.floor(ys[:-1, None]) + 1))
            colm = (pos_x >= jnp.floor(xs[:-1, None])) & \
                (pos_x < jnp.maximum(jnp.ceil(xs[1:, None]),
                                     jnp.floor(xs[:-1, None]) + 1))
            # [oh, H] x [ow, W] -> bin max via masked max
            m = rowm[:, None, :, None] & colm[None, :, None, :]
            vals = jnp.where(m[None], img[:, None, None, :, :],
                             -jnp.inf)
            return vals.max((-1, -2))        # [C, oh, ow]

        return jax.vmap(one_box)(batch_idx, x1, y1, x2, y2)

    return apply("roi_pool_op", _roi_pool, x, boxes, boxes_num,
                 _oh=int(oh), _ow=int(ow), _scale=float(spatial_scale))


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, *self._args)


class RoIAlign(Layer):
    """reference ops.py RoIAlign layer over roi_align."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._args[0],
                         spatial_scale=self._args[1], aligned=aligned)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """reference ops.py psroi_pool (R-FCN position-sensitive average
    pooling): input channels = C_out * oh * ow; bin (i, j) reads its own
    channel group."""
    from ..framework.dispatch import apply
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _psroi(xv, bx, bnum, _oh=7, _ow=7, _scale=1.0):
        N = bx.shape[0]
        C = xv.shape[1] // (_oh * _ow)
        counts = jnp.cumsum(bnum)
        batch_idx = jnp.searchsorted(counts, jnp.arange(N),
                                     side="right")
        scaled = bx * _scale
        H, W = xv.shape[2], xv.shape[3]

        def one_box(b, box):
            x1, y1, x2, y2 = box
            img = xv[b].reshape(_oh * _ow * C, H, W)
            ys = jnp.linspace(y1, y2, _oh + 1)
            xs = jnp.linspace(x1, x2, _ow + 1)
            pos_y = jnp.arange(H)[None, :]
            pos_x = jnp.arange(W)[None, :]
            rowm = (pos_y >= jnp.floor(ys[:-1, None])) & \
                (pos_y < jnp.maximum(jnp.ceil(ys[1:, None]),
                                     jnp.floor(ys[:-1, None]) + 1))
            colm = (pos_x >= jnp.floor(xs[:-1, None])) & \
                (pos_x < jnp.maximum(jnp.ceil(xs[1:, None]),
                                     jnp.floor(xs[:-1, None]) + 1))
            m = (rowm[:, None, :, None]
                 & colm[None, :, None, :])   # [oh, ow, H, W]
            imgg = img.reshape(_oh, _ow, C, H, W)
            # bin (i,j) pools channel group (i,j)
            s = jnp.sum(jnp.where(m[:, :, None], imgg, 0.0), (-1, -2))
            cnt = jnp.maximum(m.sum((-1, -2)), 1)[:, :, None]
            return jnp.moveaxis(s / cnt, -1, 0)     # [C, oh, ow]

        return jax.vmap(one_box)(batch_idx, scaled)

    return apply("psroi_pool_op", _psroi, x, boxes, boxes_num,
                 _oh=int(oh), _ow=int(ow), _scale=float(spatial_scale))


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, *self._args)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference ops.py:574 box_coder — encode boxes against priors or
    decode deltas back to boxes (center-size parameterization)."""
    from ..framework.dispatch import apply

    def _coder(pb, pbv, tb, ct=None, norm=True, ax=0):
        one = 0.0 if norm else 1.0
        pw = pb[:, 2] - pb[:, 0] + one
        ph = pb[:, 3] - pb[:, 1] + one
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if pbv is None:
            var = jnp.ones((4,), jnp.float32)
            vslice = lambda i: var[i]        # noqa: E731
        elif pbv.ndim == 1:
            vslice = lambda i: pbv[i]        # noqa: E731
        else:
            vslice = lambda i: pbv[:, i]     # noqa: E731
        if ct == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + one
            th = tb[:, 3] - tb[:, 1] + one
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            # every target against every prior: [T, P]
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :] / \
                vslice(0)
            dy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / \
                vslice(1)
            dw = jnp.log(tw[:, None] / pw[None, :]) / vslice(2)
            dh = jnp.log(th[:, None] / ph[None, :]) / vslice(3)
            return jnp.stack([dx, dy, dw, dh], -1)
        # decode: tb [N, P, 4] deltas; `ax` names the dim the priors
        # broadcast along (reference ops.py:640 — axis=0: prior per
        # column, axis=1: prior per row)
        if tb.ndim == 2:
            tb = tb[:, None, :]
        if ax == 1:
            pw, ph = pw[:, None], ph[:, None]
            pcx, pcy = pcx[:, None], pcy[:, None]
            vs = vslice
            vslice = (lambda i, _vs=vs: jnp.atleast_1d(_vs(i))[..., None]
                      if jnp.ndim(_vs(i)) else _vs(i))
        dcx = vslice(0) * tb[..., 0] * pw + pcx
        dcy = vslice(1) * tb[..., 1] * ph + pcy
        dw = jnp.exp(vslice(2) * tb[..., 2]) * pw
        dh = jnp.exp(vslice(3) * tb[..., 3]) * ph
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - one, dcy + dh * 0.5 - one],
                         -1)

    return apply("box_coder_op", _coder, prior_box, prior_box_var,
                 target_box, ct=code_type, norm=bool(box_normalized),
                 ax=int(axis))


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=[1.0], variance=[0.1, 0.1, 0.2, 0.2],
              flip=False, clip=False, steps=[0.0, 0.0], offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """reference ops.py:428 prior_box — SSD anchors per feature-map
    cell; returns (boxes [H, W, A, 4], variances [H, W, A, 4])."""
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or img_h / H
    step_w = steps[0] or img_w / W
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    mins = np.atleast_1d(np.asarray(min_sizes, np.float32))
    maxs = (np.atleast_1d(np.asarray(max_sizes, np.float32))
            if max_sizes is not None else None)
    if maxs is not None and len(maxs) != len(mins):
        raise ValueError(
            "max_sizes must pair index-wise with min_sizes "
            f"(got {len(maxs)} vs {len(mins)})")
    whs = []
    for idx, ms in enumerate(mins):
        ratio_whs = [(ms * np.sqrt(ar), ms / np.sqrt(ar)) for ar in ars]
        if maxs is None:
            whs.extend(ratio_whs)
        elif min_max_aspect_ratios_order:
            # [min, max, remaining ratios] (reference flag semantics)
            sq = np.sqrt(ms * maxs[idx])
            whs.append(ratio_whs[0])
            whs.append((sq, sq))
            whs.extend(ratio_whs[1:])
        else:
            sq = np.sqrt(ms * maxs[idx])
            whs.extend(ratio_whs)
            whs.append((sq, sq))
    whs = np.asarray(whs, np.float32)          # [A, 2]
    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)             # [H, W]
    boxes = np.stack([
        (cxg[..., None] - whs[:, 0] / 2) / img_w,
        (cyg[..., None] - whs[:, 1] / 2) / img_h,
        (cxg[..., None] + whs[:, 0] / 2) / img_w,
        (cyg[..., None] + whs[:, 1] / 2) / img_h,
    ], -1).astype(np.float32)                  # [H, W, A, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(vars_))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """reference ops.py:267 yolo_box — decode a YOLOv3 head [B, A*(5+C),
    H, W] into (boxes [B, H*W*A, 4], scores [B, H*W*A, C])."""
    from ..framework.dispatch import apply
    A = len(anchors) // 2

    def _yolo_box(xv, imgs, anc=None, C=80, thr=0.01, ds=32, clip=True,
                  sxy=1.0, ia=False, iaf=0.5):
        B, _, H, W = xv.shape
        A_ = len(anc) // 2
        if ia:
            # iou-aware head: first A channels are IoU predictions
            iou_pred = jax.nn.sigmoid(xv[:, :A_].reshape(B, A_, H, W))
            v = xv[:, A_:].reshape(B, A_, 5 + C, H, W)
        else:
            iou_pred = None
            v = xv.reshape(B, A_, 5 + C, H, W)
        gx = jnp.arange(W)[None, None, None, :]
        gy = jnp.arange(H)[None, None, :, None]
        bx = (jax.nn.sigmoid(v[:, :, 0]) * sxy - (sxy - 1) / 2 + gx) \
            / W
        by = (jax.nn.sigmoid(v[:, :, 1]) * sxy - (sxy - 1) / 2 + gy) \
            / H
        aw = jnp.asarray(anc[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anc[1::2], jnp.float32)[None, :, None, None]
        in_w, in_h = W * ds, H * ds
        bw = jnp.exp(v[:, :, 2]) * aw / in_w
        bh = jnp.exp(v[:, :, 3]) * ah / in_h
        obj = jax.nn.sigmoid(v[:, :, 4])
        if iou_pred is not None:
            obj = jnp.power(obj, 1.0 - iaf) * jnp.power(iou_pred, iaf)
        cls = jax.nn.sigmoid(v[:, :, 5:])
        score = obj[:, :, None] * cls          # [B, A, C, H, W]
        # scale to the original image
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * iw
        y1 = (by - bh / 2) * ih
        x2 = (bx + bw / 2) * iw
        y2 = (by + bh / 2) * ih
        if clip:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1)     # [B, A, H, W, 4]
        boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(B, -1, 4)
        score = score.transpose(0, 3, 4, 1, 2).reshape(B, -1, C)
        keep = (obj.transpose(0, 2, 3, 1).reshape(B, -1) > thr)
        score = score * keep[..., None]
        return boxes, score

    return apply("yolo_box_op", _yolo_box, x, img_size,
                 anc=tuple(anchors), C=int(class_num),
                 thr=float(conf_thresh), ds=int(downsample_ratio),
                 clip=bool(clip_bbox), sxy=float(scale_x_y),
                 ia=bool(iou_aware), iaf=float(iou_aware_factor))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference ops.py yolo_loss (yolov3_loss op): per-cell anchor
    assignment by best IoU with each gt, BCE on xy/obj/class, L1 on wh,
    objectness ignore above ignore_thresh. Returns [B] loss."""
    from ..framework.dispatch import apply
    A = len(anchor_mask)

    def _loss(xv, gtb, gtl, gts, anc=None, msk=None, C=20, ig=0.7,
              ds=32, sxy=1.0, smooth=True):
        B, _, H, W = xv.shape
        A_ = len(msk)
        v = xv.reshape(B, A_, 5 + C, H, W)
        in_w, in_h = W * ds, H * ds
        # gt in [0,1] center-size (the reference contract): [B, G, 4]
        gx, gy, gw, gh = (gtb[..., 0], gtb[..., 1], gtb[..., 2],
                          gtb[..., 3])
        valid = (gw > 0) & (gh > 0)
        # best anchor (over the FULL anchor set) per gt by shape IoU
        all_aw = jnp.asarray(anc[0::2], jnp.float32) / in_w
        all_ah = jnp.asarray(anc[1::2], jnp.float32) / in_h
        inter = (jnp.minimum(gw[..., None], all_aw)
                 * jnp.minimum(gh[..., None], all_ah))
        union = gw[..., None] * gh[..., None] + all_aw * all_ah - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)
        mask_arr = jnp.asarray(msk)
        # local anchor slot of the best anchor (or -1)
        local = jnp.argmax(
            (best[..., None] == mask_arr).astype(jnp.int32), -1)
        has_local = (best[..., None] == mask_arr).any(-1) & valid
        ci = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        cj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        # route invalid gts out of bounds and DROP them: scatter-max
        # would clamp negative targets (log(gw/aw) < 0) to the zero base
        ci_s = jnp.where(has_local, ci, W)
        bidx = jnp.arange(B)[:, None] * jnp.ones_like(local)

        def scat(upd):
            base = jnp.zeros((B, A_, H, W), jnp.float32)
            return base.at[bidx, local, cj, ci_s].set(upd, mode="drop")

        score_w = (jnp.ones_like(gx) if gts is None
                   else gts.astype(jnp.float32))
        obj_tgt = scat(score_w)                # mixup gt_score target
        tx = scat(gx * W - ci)
        ty = scat(gy * H - cj)
        aw_sel = all_aw[mask_arr][None, :, None, None]
        ah_sel = all_ah[mask_arr][None, :, None, None]
        tw = scat(jnp.log(jnp.maximum(gw, 1e-9)
                          / jnp.maximum(all_aw[best], 1e-9)))
        th = scat(jnp.log(jnp.maximum(gh, 1e-9)
                          / jnp.maximum(all_ah[best], 1e-9)))
        scale = scat(2.0 - gw * gh)
        cls_tgt = jnp.zeros((B, A_, H, W, C), jnp.float32)
        cls_tgt = cls_tgt.at[bidx, local, cj, ci_s,
                             jnp.clip(gtl, 0, C - 1)].set(
            1.0, mode="drop")
        if smooth:
            delta = 1.0 / C
            cls_tgt = jnp.where(obj_tgt[..., None] > 0,
                                cls_tgt * (1 - delta) + delta * 0.5 / C,
                                cls_tgt)

        def bce(logit, tgt):
            return jax.nn.softplus(logit) - tgt * logit

        px, py = v[:, :, 0], v[:, :, 1]
        pw, ph = v[:, :, 2], v[:, :, 3]
        pobj = v[:, :, 4]
        pcls = v[:, :, 5:].transpose(0, 1, 3, 4, 2)
        pos = obj_tgt > 0
        w_map = jnp.where(pos, obj_tgt, 1.0)   # per-gt mixup weight
        loss_xy = jnp.where(pos,
                            w_map * scale * (bce(px, tx) + bce(py, ty)),
                            0.0)
        loss_wh = jnp.where(pos,
                            w_map * scale * 0.5 * (jnp.abs(pw - tw)
                                                   + jnp.abs(ph - th)),
                            0.0)
        # ignore mask: predicted boxes with IoU>thresh against ANY gt
        bx = (jax.nn.sigmoid(px) + jnp.arange(W)[None, None, None, :]) \
            / W
        by = (jax.nn.sigmoid(py) + jnp.arange(H)[None, None, :, None]) \
            / H
        bw = jnp.exp(jnp.clip(pw, -10, 10)) * aw_sel
        bh = jnp.exp(jnp.clip(ph, -10, 10)) * ah_sel
        bx1, by1 = bx - bw / 2, by - bh / 2
        bx2, by2 = bx + bw / 2, by + bh / 2
        gx1, gy1 = gx - gw / 2, gy - gh / 2
        gx2, gy2 = gx + gw / 2, gy + gh / 2
        ix1 = jnp.maximum(bx1[..., None], gx1[:, None, None, None, :])
        iy1 = jnp.maximum(by1[..., None], gy1[:, None, None, None, :])
        ix2 = jnp.minimum(bx2[..., None], gx2[:, None, None, None, :])
        iy2 = jnp.minimum(by2[..., None], gy2[:, None, None, None, :])
        iw_ = jnp.maximum(ix2 - ix1, 0)
        ih_ = jnp.maximum(iy2 - iy1, 0)
        inter_p = iw_ * ih_
        union_p = (bw * bh)[..., None] + (gw * gh)[:, None, None, None,
                                                   :] - inter_p
        iou_p = jnp.where(valid[:, None, None, None, :],
                          inter_p / jnp.maximum(union_p, 1e-10), 0.0)
        ignore = (iou_p.max(-1) > ig) & ~pos
        loss_obj = jnp.where(ignore, 0.0, bce(pobj, obj_tgt))
        loss_cls = (jnp.where(pos[..., None], bce(pcls, cls_tgt), 0.0)
                    * w_map[..., None]).sum(-1)
        total = (loss_xy + loss_wh + loss_obj + loss_cls)
        return total.sum((1, 2, 3))

    gts = gt_score
    return apply("yolo_loss_op", _loss, x, gt_box, gt_label, gts,
                 anc=tuple(anchors), msk=tuple(anchor_mask),
                 C=int(class_num), ig=float(ignore_thresh),
                 ds=int(downsample_ratio), sxy=float(scale_x_y),
                 smooth=bool(use_label_smooth))


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """reference ops.py matrix_nms (SOLOv2): parallel decayed-score NMS
    — decay_j = min_i f(iou_ij) / max_i f(iou_i,label) over higher-
    scored boxes. Host-side selection (data-dependent output count)."""
    bv = np.asarray(bboxes._value if isinstance(bboxes, Tensor)
                    else bboxes)
    sv = np.asarray(scores._value if isinstance(scores, Tensor)
                    else scores)
    outs, idxs, nums = [], [], []
    B, C, N = sv.shape
    for b in range(B):
        cand = []
        for c in range(C):
            if c == background_label:
                continue
            sc = sv[b, c]
            keep = np.nonzero(sc > score_threshold)[0]
            for i in keep:
                cand.append((float(sc[i]), c, i))
        cand.sort(reverse=True)
        cand = cand[:nms_top_k]
        if not cand:
            outs.append(np.zeros((0, 6), np.float32))
            idxs.append(np.zeros((0,), np.int64))
            nums.append(0)
            continue
        boxes_b = np.stack([bv[b, i] for _s, _c, i in cand])
        scores_b = np.asarray([s for s, _c, _i in cand], np.float32)
        labels_b = np.asarray([c for _s, c, _i in cand])
        x1, y1, x2, y2 = boxes_b.T
        one = 0.0 if normalized else 1.0
        area = (x2 - x1 + one) * (y2 - y1 + one)
        n = len(cand)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        inter = np.maximum(ix2 - ix1 + one, 0) * \
            np.maximum(iy2 - iy1 + one, 0)
        iou = inter / (area[:, None] + area[None, :] - inter)
        same = labels_b[:, None] == labels_b[None, :]
        # pair (i, j) is "live" when i is higher-scored than j (i < j in
        # the desc-sorted order) and same-class
        live = np.triu(np.ones((n, n), bool), 1) & same
        M = np.where(live, iou, 0.0)

        def f(x):
            return (np.exp(-(x ** 2) / gaussian_sigma) if use_gaussian
                    else 1.0 - x)

        # SOLOv2 eq. 5: decay_j = min_{i<j} f(iou_ij) / f(comp_i),
        # comp_i = max_{k<i} iou_ki
        comp = M.max(0)
        decay = np.where(live,
                         f(iou) / np.maximum(f(comp)[:, None], 1e-10),
                         np.inf)
        decay_j = np.minimum(decay.min(0), 1.0)
        dscores = scores_b * np.where(np.isfinite(decay_j), decay_j,
                                      1.0)
        keep = dscores > post_threshold
        order = np.argsort(-dscores[keep])[:keep_top_k]
        sel = np.nonzero(keep)[0][order]
        det = np.concatenate(
            [labels_b[sel, None].astype(np.float32),
             dscores[sel, None], boxes_b[sel]], 1)
        outs.append(det.astype(np.float32))
        idxs.append(np.asarray([cand[i][2] for i in sel], np.int64))
        nums.append(len(sel))
    out = Tensor(jnp.asarray(np.concatenate(outs)
                             if outs else np.zeros((0, 6), np.float32)))
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    index = Tensor(jnp.asarray(np.concatenate(idxs)
                               if idxs else np.zeros(0, np.int64)))
    if return_index:
        return (out, index, rois_num) if return_rois_num else (out,
                                                               index)
    return (out, rois_num) if return_rois_num else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale,
                             pixel_offset=False, rois_num=None,
                             name=None):
    """reference ops.py distribute_fpn_proposals — assign each RoI to an
    FPN level by sqrt-area scale (FPN paper eq. 1); returns per-level
    RoI lists + the restore index. Host-side selection."""
    rv = np.asarray(fpn_rois._value if isinstance(fpn_rois, Tensor)
                    else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    w = rv[:, 2] - rv[:, 0] + off
    h = rv[:, 3] - rv[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, nums, order = [], [], []
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rv[sel])))
        nums.append(Tensor(jnp.asarray(
            np.asarray([len(sel)], np.int32))))
        order.extend(sel.tolist())
    restore = np.argsort(np.asarray(order)).astype(np.int32)
    return outs, Tensor(jnp.asarray(restore[:, None])), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors,
                       variances, pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """reference ops.py generate_proposals (RPN): decode deltas against
    anchors, clip to the image, drop tiny boxes, top-k + NMS. Host-side
    selection pipeline over jnp box math."""
    sv = np.asarray(scores._value if isinstance(scores, Tensor)
                    else scores)             # [B, A, H, W]
    dv = np.asarray(bbox_deltas._value if isinstance(bbox_deltas, Tensor)
                    else bbox_deltas)        # [B, 4A, H, W]
    im = np.asarray(img_size._value if isinstance(img_size, Tensor)
                    else img_size)           # [B, 2]
    av = np.asarray(anchors._value if isinstance(anchors, Tensor)
                    else anchors).reshape(-1, 4)
    vv = np.asarray(variances._value if isinstance(variances, Tensor)
                    else variances).reshape(-1, 4)
    B = sv.shape[0]
    off = 1.0 if pixel_offset else 0.0
    rois_out, num_out, score_out = [], [], []
    for b in range(B):
        s = sv[b].transpose(1, 2, 0).reshape(-1)
        d = dv[b].reshape(-1, 4, sv.shape[2],
                          sv.shape[3]).transpose(2, 3, 0, 1).reshape(
            -1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s_k, d_k, a_k, v_k = s[order], d[order], av[order % len(av)], \
            vv[order % len(vv)]
        aw = a_k[:, 2] - a_k[:, 0] + off
        ah = a_k[:, 3] - a_k[:, 1] + off
        acx = a_k[:, 0] + aw * 0.5
        acy = a_k[:, 1] + ah * 0.5
        cx = v_k[:, 0] * d_k[:, 0] * aw + acx
        cy = v_k[:, 1] * d_k[:, 1] * ah + acy
        wfull = np.exp(np.minimum(v_k[:, 2] * d_k[:, 2], 10.0)) * aw
        hfull = np.exp(np.minimum(v_k[:, 3] * d_k[:, 3], 10.0)) * ah
        x1 = np.clip(cx - wfull / 2, 0, im[b, 1] - off)
        y1 = np.clip(cy - hfull / 2, 0, im[b, 0] - off)
        x2 = np.clip(cx + wfull / 2 - off, 0, im[b, 1] - off)
        y2 = np.clip(cy + hfull / 2 - off, 0, im[b, 0] - off)
        keep = ((x2 - x1 + off) >= min_size) & \
            ((y2 - y1 + off) >= min_size)
        boxes = np.stack([x1, y1, x2, y2], 1)[keep]
        s_k = s_k[keep]
        # standard hard NMS
        sel = []
        idx = np.argsort(-s_k)
        areas = (boxes[:, 2] - boxes[:, 0] + off) * \
            (boxes[:, 3] - boxes[:, 1] + off)
        while len(idx) and len(sel) < post_nms_top_n:
            i = idx[0]
            sel.append(i)
            if len(idx) == 1:
                break
            rest = idx[1:]
            ix1 = np.maximum(boxes[i, 0], boxes[rest, 0])
            iy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
            ix2 = np.minimum(boxes[i, 2], boxes[rest, 2])
            iy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
            inter = np.maximum(ix2 - ix1 + off, 0) * \
                np.maximum(iy2 - iy1 + off, 0)
            iou = inter / (areas[i] + areas[rest] - inter)
            idx = rest[iou <= nms_thresh]
        rois_out.append(boxes[sel])
        score_out.append(s_k[sel])
        num_out.append(len(sel))
    rois = Tensor(jnp.asarray(np.concatenate(rois_out)
                              if rois_out else np.zeros((0, 4),
                                                        np.float32)))
    rscores = Tensor(jnp.asarray(np.concatenate(score_out)
                                 if score_out else np.zeros(
                                     0, np.float32)))
    nums = Tensor(jnp.asarray(np.asarray(num_out, np.int32)))
    if return_rois_num:
        return rois, rscores, nums
    return rois, rscores
