"""paddle_tpu.vision — model zoo, transforms, datasets, vision ops.

Reference analog: python/paddle/vision/ (models/resnet.py:195 et al.,
transforms/, datasets/, ops.py). BASELINE config 4's ResNet-50 path lives
here.
"""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .ops import nms, roi_align  # noqa: F401


_image_backend = {"name": "pil"}


def set_image_backend(backend):
    """reference vision/image.py set_image_backend (cv2 is not in this
    image; pil and numpy are the working backends)."""
    if backend not in ("pil", "cv2", "numpy"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'numpy'], "
            f"but got {backend}")
    if backend == "cv2":
        raise NotImplementedError("cv2 is not installed in this image")
    _image_backend["name"] = backend


def get_image_backend():
    return _image_backend["name"]


def image_load(path, backend=None):
    """reference vision/image.py image_load — PIL image (pil backend)
    or HWC numpy array (numpy backend)."""
    from PIL import Image
    backend = backend or get_image_backend()
    if backend == "cv2":
        raise NotImplementedError("cv2 is not installed in this image")
    img = Image.open(path)
    if backend == "numpy":
        import numpy as np
        return np.array(img)
    return img
