"""paddle_tpu.vision — model zoo, transforms, datasets, vision ops.

Reference analog: python/paddle/vision/ (models/resnet.py:195 et al.,
transforms/, datasets/, ops.py). BASELINE config 4's ResNet-50 path lives
here.
"""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .ops import nms, roi_align  # noqa: F401


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "numpy"):
        raise ValueError(f"unknown image backend {backend!r}")


def get_image_backend():
    return "numpy"
