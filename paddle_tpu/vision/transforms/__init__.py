"""Vision transforms (reference python/paddle/vision/transforms/ —
numpy-backed host preprocessing; the DataLoader runs these per sample)."""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence

import numpy as np

from ...framework.tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Transpose", "Resize",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "BaseTransform", "to_tensor_transform",
           "normalize", "resize", "hflip", "center_crop"]


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    """Chain transforms (reference transforms.py Compose)."""

    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_float(img):
    img = np.asarray(img)
    if img.dtype == np.uint8:
        return img.astype(np.float32) / 255.0
    return img.astype(np.float32)


class ToTensor(BaseTransform):
    """HWC uint8/float → CHW float32 Tensor in [0,1] (reference
    ToTensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_float(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return to_tensor(np.ascontiguousarray(img))


def to_tensor_transform(img, data_format="CHW"):
    return ToTensor(data_format)(img)


class Normalize(BaseTransform):
    """(x - mean) / std per channel (reference Normalize)."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1)
        self.std = np.asarray(std, np.float32).reshape(-1)
        self.data_format = data_format

    def __call__(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return to_tensor((img - self.mean.reshape(shape))
                         / self.std.reshape(shape))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


class Transpose(BaseTransform):
    """HWC→CHW permute (reference Transpose)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, self.order)


def _resize_np(img, size):
    """Nearest+bilinear numpy resize (no PIL/cv2 dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter side → size, keep aspect (the reference convention)
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    img_f = img.astype(np.float32)
    if img.ndim == 2:
        img_f = img_f[:, :, None]
    out = ((1 - wy)[..., None] * ((1 - wx)[..., None] * img_f[y0][:, x0]
                                  + wx[..., None] * img_f[y0][:, x1])
           + wy[..., None] * ((1 - wx)[..., None] * img_f[y1][:, x0]
                              + wx[..., None] * img_f[y1][:, x1]))
    if img.ndim == 2:
        out = out[:, :, 0]
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(np.asarray(img), self.size)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


def center_crop(img, size):
    return CenterCrop(size)(np.asarray(img))


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        if self.padding:
            p = self.padding
            p = (p, p) if isinstance(p, numbers.Number) else p
            pads = [(p[0], p[0]), (p[1], p[1])] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(0, th - h), max(0, tw - w)
            pads = [(ph - ph // 2, ph // 2), (pw - pw // 2, pw // 2)] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
            h, w = img.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


# ---------------------------------------------------------------------
# functional tail (reference transforms/functional.py — numpy/scipy
# host implementations; inputs HWC or HW numpy arrays / PIL images)
# ---------------------------------------------------------------------
def vflip(img):
    """reference functional.py vflip."""
    return np.asarray(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    """reference functional.py pad — padding int | [l/r, t/b] |
    [left, top, right, bottom] (the reference order)."""
    img = np.asarray(img)
    if isinstance(padding, numbers.Number):
        l = r = t = b = int(padding)
    elif len(padding) == 2:
        l = r = int(padding[0])
        t = b = int(padding[1])
    else:
        l, t, r, b = (int(p) for p in padding)
    spec = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, spec, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, spec, mode=mode)


def to_grayscale(img, num_output_channels=1):
    """reference functional.py to_grayscale — ITU-R 601-2 luma."""
    img = np.asarray(img)
    if img.ndim == 2:
        g = img.astype(np.float32)
    else:
        g = (0.299 * img[..., 0] + 0.587 * img[..., 1]
             + 0.114 * img[..., 2]).astype(np.float32)
    if img.dtype == np.uint8:
        g = np.clip(np.round(g), 0, 255).astype(np.uint8)
    out = g[..., None]
    if num_output_channels == 3:
        out = np.repeat(out, 3, axis=-1)
    return out


def rotate(img, angle, interpolation="nearest", expand=False,
           center=None, fill=0):
    """reference functional.py rotate (degrees, counter-clockwise);
    `center` pivots the rotation (the default is the image center)."""
    from scipy import ndimage
    img = np.asarray(img)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}[interpolation]
    if center is not None and not expand:
        # off-center pivot == affine rotation about that pivot
        return affine(img, angle, (0, 0), 1.0, (0, 0),
                      interpolation=interpolation, fill=fill,
                      center=center)
    if center is not None and expand:
        raise NotImplementedError(
            "rotate with both center and expand is unsupported "
            "(the reference PIL backend has the same restriction)")
    axes = (1, 0)
    return ndimage.rotate(img, angle, axes=axes, reshape=bool(expand),
                          order=order, mode="constant", cval=fill)


def _affine_matrix(angle, translate, scale, shear, center):
    a = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # torch/paddle convention: M = T(center) T(translate) R(angle)
    # Shear Scale T(-center)
    # torchvision/paddle RSS decomposition (functional.py
    # _get_inverse_affine_matrix)
    rot = np.array([
        [np.cos(a - sy) / np.cos(sy),
         -np.cos(a - sy) * np.tan(sx) / np.cos(sy) - np.sin(a)],
        [np.sin(a - sy) / np.cos(sy),
         -np.sin(a - sy) * np.tan(sx) / np.cos(sy) + np.cos(a)],
    ]) * scale
    m = np.eye(3)
    m[:2, :2] = rot
    m[0, 2] = cx + tx - rot[0, 0] * cx - rot[0, 1] * cy
    m[1, 2] = cy + ty - rot[1, 0] * cx - rot[1, 1] * cy
    return m


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """reference functional.py affine: rotate/translate/scale/shear
    about the image center (inverse-map resampling)."""
    from scipy import ndimage
    img = np.asarray(img)
    h, w = img.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    m = _affine_matrix(angle, translate, scale, shear, center)
    minv = np.linalg.inv(m)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}[interpolation]
    # map output (x, y) -> input; ndimage works in (row, col)
    mat = np.array([[minv[1, 1], minv[1, 0]],
                    [minv[0, 1], minv[0, 0]]])
    off = np.array([minv[1, 2], minv[0, 2]])

    def warp_plane(p):
        return ndimage.affine_transform(p, mat, offset=off, order=order,
                                        mode="constant", cval=fill)

    if img.ndim == 2:
        return warp_plane(img)
    return np.stack([warp_plane(img[..., c])
                     for c in range(img.shape[-1])], axis=-1)


def _perspective_coeffs(startpoints, endpoints):
    # solve the 8-dof homography mapping endpoints -> startpoints
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """reference functional.py perspective — warp so that startpoints
    map onto endpoints."""
    from scipy import ndimage
    img = np.asarray(img)
    h, w = img.shape[:2]
    c = _perspective_coeffs(startpoints, endpoints)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}[interpolation]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = c[6] * xs + c[7] * ys + 1.0
    src_x = (c[0] * xs + c[1] * ys + c[2]) / den
    src_y = (c[3] * xs + c[4] * ys + c[5]) / den
    coords = np.stack([src_y.ravel(), src_x.ravel()])

    def warp_plane(p):
        out = ndimage.map_coordinates(p.astype(np.float32), coords,
                                      order=order, mode="constant",
                                      cval=fill)
        return out.reshape(h, w).astype(p.dtype)

    if img.ndim == 2:
        return warp_plane(img)
    return np.stack([warp_plane(img[..., ch])
                     for ch in range(img.shape[-1])], axis=-1)


# ------------------------------------------------------ color adjusters
def _blend(a, b, factor):
    out = a.astype(np.float32) * factor + b.astype(np.float32) * (
        1.0 - factor)
    return out


def _finish_color(img, ref):
    if np.asarray(ref).dtype == np.uint8:
        return np.clip(np.round(img), 0, 255).astype(np.uint8)
    return img.astype(np.float32)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img)
    return _finish_color(arr.astype(np.float32) * brightness_factor, arr)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img)
    gray = to_grayscale(arr).astype(np.float32)
    mean = gray.mean()
    return _finish_color(_blend(arr, np.full_like(
        arr, mean, dtype=np.float32), contrast_factor), arr)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img)
    gray = to_grayscale(arr, 3).astype(np.float32)
    return _finish_color(_blend(arr, gray, saturation_factor), arr)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5] — shift in HSV space (reference
    functional adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img)
    f = arr.astype(np.float32) / (255.0 if arr.dtype == np.uint8
                                  else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f[..., :3].max(-1)
    minc = f[..., :3].min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    dd = np.maximum(d, 1e-12)
    # priority select — a tied max channel must pick ONE branch
    hue = np.where(
        maxc == r, ((g - b) / dd) % 6,
        np.where(maxc == g, (b - r) / dd + 2, (r - g) / dd + 4))
    hue = np.where(d > 0, hue, 0.0) / 6.0
    hue = (hue + hue_factor) % 1.0
    # hsv -> rgb
    i = np.floor(hue * 6.0)
    fphase = hue * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * fphase)
    t = v * (1 - s * (1 - fphase))
    i = (i.astype(np.int32) % 6)[..., None]
    rgb = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    if arr.dtype == np.uint8:
        return np.clip(np.round(rgb * 255.0), 0, 255).astype(np.uint8)
    return rgb.astype(np.float32)


# ------------------------------------------------------- class transforms
class Pad(BaseTransform):
    """reference transforms.py Pad."""

    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    """reference transforms.py BrightnessTransform — factor drawn from
    [max(0, 1-value), 1+value]."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _factor(self):
        return random.uniform(max(0.0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    """factor drawn from [-value, value], value in [0, 0.5]."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """reference transforms.py ColorJitter — random order of the four
    adjusters."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomResizedCrop(BaseTransform):
    """reference transforms.py RandomResizedCrop — random area/aspect
    crop then resize."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                crop = img[top:top + ch, left:left + cw]
                return _resize_np(crop, self.size)
        # fallback: center crop of the feasible aspect
        return _resize_np(img, self.size)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        return rotate(img, random.uniform(*self.degrees), **self.kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.kw = dict(interpolation=interpolation, fill=fill,
                       center=center)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        angle = random.uniform(*self.degrees)
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        else:
            tx = ty = 0.0
        sc = random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        else:
            sh = (random.uniform(-self.shear[0], self.shear[0]),
                  random.uniform(-self.shear[1], self.shear[1])
                  if len(self.shear) > 1 else 0.0)
        return affine(img, angle, (tx, ty), sc, sh, **self.kw)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return np.asarray(img)
        img = np.asarray(img)
        h, w = img.shape[:2]
        d = self.distortion_scale
        half_w, half_h = int(w * d / 2), int(h * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [
            (random.randint(0, half_w), random.randint(0, half_h)),
            (w - 1 - random.randint(0, half_w),
             random.randint(0, half_h)),
            (w - 1 - random.randint(0, half_w),
             h - 1 - random.randint(0, half_h)),
            (random.randint(0, half_w),
             h - 1 - random.randint(0, half_h)),
        ]
        return perspective(img, start, end, self.interpolation,
                           self.fill)


class RandomErasing(BaseTransform):
    """reference transforms.py RandomErasing — zero/mean/random-fill a
    random rectangle (applies to CHW tensors or HWC arrays)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        chw_tensor = isinstance(img, Tensor)
        arr = np.array(img.numpy() if chw_tensor else img)
        if random.random() >= self.prob:
            return to_tensor(arr) if chw_tensor else arr
        if chw_tensor or (arr.ndim == 3 and arr.shape[0] in (1, 3)
                          and arr.shape[-1] not in (1, 3)):
            h_ax, w_ax = 1, 2                # CHW
        else:
            h_ax, w_ax = 0, 1                # HWC / HW
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                sl = [slice(None)] * arr.ndim
                sl[h_ax] = slice(top, top + eh)
                sl[w_ax] = slice(left, left + ew)
                if self.value == "random":
                    arr[tuple(sl)] = np.random.randn(
                        *arr[tuple(sl)].shape).astype(arr.dtype)
                else:
                    arr[tuple(sl)] = self.value
                break
        return to_tensor(arr) if chw_tensor else arr

    def _apply_image(self, img):
        return self.__call__(img)


__all__ += ["vflip", "pad", "to_grayscale", "rotate", "affine",
            "perspective", "adjust_brightness", "adjust_contrast",
            "adjust_saturation", "adjust_hue", "Pad", "Grayscale",
            "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "ColorJitter",
            "RandomResizedCrop", "RandomRotation", "RandomAffine",
            "RandomPerspective", "RandomErasing"]


def crop(img, top, left, height, width):
    """reference functional.py crop."""
    return np.asarray(img)[top:top + height, left:left + width].copy()


def erase(img, i, j, h, w, v, inplace=False):
    """reference functional.py erase — fill img[i:i+h, j:j+w] with v
    (HWC arrays / CHW Tensors)."""
    if isinstance(img, Tensor):
        arr = np.array(img.numpy())
        arr[..., i:i + h, j:j + w] = v
        return to_tensor(arr)
    arr = np.asarray(img) if inplace else np.array(img)
    arr[i:i + h, j:j + w] = v
    return arr


__all__ += ["crop", "erase"]
