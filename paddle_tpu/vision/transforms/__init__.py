"""Vision transforms (reference python/paddle/vision/transforms/ —
numpy-backed host preprocessing; the DataLoader runs these per sample)."""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence

import numpy as np

from ...framework.tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Transpose", "Resize",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "BaseTransform", "to_tensor_transform",
           "normalize", "resize", "hflip", "center_crop"]


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    """Chain transforms (reference transforms.py Compose)."""

    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_float(img):
    img = np.asarray(img)
    if img.dtype == np.uint8:
        return img.astype(np.float32) / 255.0
    return img.astype(np.float32)


class ToTensor(BaseTransform):
    """HWC uint8/float → CHW float32 Tensor in [0,1] (reference
    ToTensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_float(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return to_tensor(np.ascontiguousarray(img))


def to_tensor_transform(img, data_format="CHW"):
    return ToTensor(data_format)(img)


class Normalize(BaseTransform):
    """(x - mean) / std per channel (reference Normalize)."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1)
        self.std = np.asarray(std, np.float32).reshape(-1)
        self.data_format = data_format

    def __call__(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return to_tensor((img - self.mean.reshape(shape))
                         / self.std.reshape(shape))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


class Transpose(BaseTransform):
    """HWC→CHW permute (reference Transpose)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, self.order)


def _resize_np(img, size):
    """Nearest+bilinear numpy resize (no PIL/cv2 dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter side → size, keep aspect (the reference convention)
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    img_f = img.astype(np.float32)
    if img.ndim == 2:
        img_f = img_f[:, :, None]
    out = ((1 - wy)[..., None] * ((1 - wx)[..., None] * img_f[y0][:, x0]
                                  + wx[..., None] * img_f[y0][:, x1])
           + wy[..., None] * ((1 - wx)[..., None] * img_f[y1][:, x0]
                              + wx[..., None] * img_f[y1][:, x1]))
    if img.ndim == 2:
        out = out[:, :, 0]
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(np.asarray(img), self.size)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


def center_crop(img, size):
    return CenterCrop(size)(np.asarray(img))


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        if self.padding:
            p = self.padding
            p = (p, p) if isinstance(p, numbers.Number) else p
            pads = [(p[0], p[0]), (p[1], p[1])] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(0, th - h), max(0, tw - w)
            pads = [(ph - ph // 2, ph // 2), (pw - pw // 2, pw // 2)] + \
                [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
            h, w = img.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()
