"""incubate.nn (reference python/paddle/incubate/nn/ — fused transformer
layers + memory-efficient attention; here they live in the core nn/kernels,
re-exported at the reference paths)."""
from ..nn.layers.transformer import (  # noqa: F401
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
    MultiHeadAttention as FusedMultiHeadAttention)
from ..kernels.flash_attention import (  # noqa: F401
    flash_attention as memory_efficient_attention)

from ..parallel.moe import MoELayer  # noqa: F401
from .fused_multi_transformer import FusedMultiTransformer  # noqa: F401
