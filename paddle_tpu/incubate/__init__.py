"""paddle_tpu.incubate (reference python/paddle/incubate/ — experimental
APIs that graduated into the core here; this namespace re-exports them at
the reference's import paths)."""
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401


def _softmax_mask(x, mask):
    import jax
    return jax.nn.softmax(x + mask, axis=-1)


def softmax_mask_fuse(x, mask, name=None):
    """Reference incubate/operators/softmax_mask_fuse.py — one op here;
    XLA fuses the mask+softmax chain natively."""
    from ..framework.dispatch import apply
    return apply("softmax_mask_fuse", _softmax_mask, x, mask)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Reference incubate graph message passing (moved to geometric)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


from . import multiprocessing  # noqa: E402,F401
from . import checkpoint  # noqa: E402,F401
