"""paddle_tpu.incubate (reference python/paddle/incubate/ — experimental
APIs that graduated into the core here; this namespace re-exports them at
the reference's import paths)."""
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401


def _softmax_mask(x, mask):
    import jax
    return jax.nn.softmax(x + mask, axis=-1)


def softmax_mask_fuse(x, mask, name=None):
    """Reference incubate/operators/softmax_mask_fuse.py — one op here;
    XLA fuses the mask+softmax chain natively."""
    from ..framework.dispatch import apply
    return apply("softmax_mask_fuse", _softmax_mask, x, mask)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Reference incubate graph message passing (moved to geometric)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


from . import multiprocessing  # noqa: E402,F401
from . import checkpoint  # noqa: E402,F401


def softmax_mask_fuse_upper_triangle(x):
    """Reference incubate softmax_mask_fuse_upper_triangle — causal-mask
    softmax over [B, H, S, S] scores (XLA fuses the chain)."""
    import jax
    import jax.numpy as jnp
    from ..framework.dispatch import apply

    def _op(scores):
        S = scores.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        return jax.nn.softmax(jnp.where(mask, scores, -1e30), axis=-1)

    return apply("softmax_mask_fuse_upper_triangle", _op, x)


def identity_loss(x, reduction="none"):
    """reference incubate/nn/loss.py identity_loss — marks x as a loss
    (IPU artifact); reduces per `reduction`."""
    from ..nn.functional.extra import _reduce
    from ..framework.dispatch import apply
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    return apply("identity_loss", lambda v, red_=None: _reduce(v, red_),
                 x, red_=red)


from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401
from .ps_embedding import HostShardedEmbedding  # noqa: E402,F401
# graph ops graduated into paddle_tpu.geometric; re-export at the
# incubate paths the reference still documents
from ..geometric import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min,
    sample_neighbors as graph_sample_neighbors,
    reindex_graph as graph_reindex)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference incubate/operators/graph_khop_sampler — multi-hop
    neighbor sampling with one shared local-id space: input nodes get
    ids first, then first-seen sampled neighbors; edges are (src local,
    dst local) across all hops. Host-side like the geometric samplers
    (data-dependent output counts)."""
    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True) is unsupported; use "
            "geometric.sample_neighbors(return_eids=True) per hop")
    import numpy as np
    from ..framework.tensor import Tensor
    from ..geometric import sample_neighbors

    def _host(t):
        return np.asarray(t._value if isinstance(t, Tensor) else t
                          ).reshape(-1)

    id2local = {}
    out_nodes = []

    def local(g):
        g = int(g)
        if g not in id2local:
            id2local[g] = len(out_nodes)
            out_nodes.append(g)
        return id2local[g]

    frontier = _host(input_nodes)
    for g in frontier:
        local(g)
    src_l, dst_l, counts = [], [], []
    for k in sample_sizes:
        nbr, cnt = sample_neighbors(row, colptr, Tensor(
            np.asarray(frontier, np.int64)), sample_size=k)
        nbr_h, cnt_h = _host(nbr), _host(cnt)
        counts.append(cnt_h)
        pos = 0
        for node, c in zip(frontier, cnt_h):
            dloc = local(node)
            for g in nbr_h[pos:pos + int(c)]:
                src_l.append(local(g))
                dst_l.append(dloc)
            pos += int(c)
        # next frontier: the distinct nodes just discovered
        frontier = np.unique(nbr_h)
    dt = np.int64
    return (Tensor(np.asarray(src_l, dt)),
            Tensor(np.asarray(dst_l, dt)),
            Tensor(np.asarray(out_nodes, dt)),
            Tensor(np.concatenate(counts).astype(dt)
                   if counts else np.zeros(0, dt)))
