"""incubate optimizer wrappers (reference
python/paddle/incubate/optimizer/lookahead.py:25 LookAhead,
modelaverage.py:28 ModelAverage) — eager wrappers over any inner
optimizer."""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp


class LookAhead:
    """reference lookahead.py:25 — slow weights track the fast weights:
    every k inner steps, slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step = 0
        self._slow = {}

    @property
    def _params(self):
        return self.inner_optimizer._parameter_list

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k:
            return
        for p in self._params:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._value
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class ModelAverage:
    """reference modelaverage.py:28 — running average of parameters over
    a sliding window; apply()/restore() swap the averages in for
    evaluation."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._params = list(parameters) if parameters is not None else []
        self._sum = {}
        self._count = {}
        self._updates = 0
        self._backup = {}

    def step(self):
        self._updates += 1
        window = max(self._min_w,
                     min(self._max_w, self._updates * self._rate))
        for p in self._params:
            s = self._sum.get(id(p), jnp.zeros_like(p._value))
            c = self._count.get(id(p), 0)
            s = s + p._value
            c += 1
            if c > window:
                # restart the accumulation window (the reference rolls
                # sum_1/sum_2/sum_3 blocks; a restart bounds the same
                # window length)
                s = p._value.astype(s.dtype)
                c = 1
            self._sum[id(p)] = s
            self._count[id(p)] = c

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            c = self._count.get(id(p), 0)
            if c:
                p._value = (self._sum[id(p)] / c).astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = {}
