"""paddle_tpu.incubate.multiprocessing — shared-memory tensor transport.

Reference analog: python/paddle/incubate/multiprocessing (CUDA-IPC /
shared-memory tensor pickling for DataLoader workers,
reductions.py). Here the shared-memory transport is the native SPSC ring
the DataLoader already uses (io/_native/shm_ring.cpp) — exposed for
direct use by custom worker topologies. A real module (not just an
attribute) so `import paddle_tpu.incubate.multiprocessing` works like
the reference idiom.
"""
from __future__ import annotations


def shm_ring(n_slots: int = 4, slot_bytes: int = 1 << 22):
    """A fresh SPSC shared-memory ring (create BEFORE fork)."""
    from ..io.shm_ring import ShmRing
    return ShmRing(n_slots=n_slots, slot_bytes=slot_bytes)


def available() -> bool:
    from ..io.shm_ring import available as _a
    return _a()
