"""incubate.nn (reference python/paddle/incubate/nn/__init__.py:27 —
fused transformer layers + memory-efficient attention + the functional
fused-op surface). The attention/encoder classes live in the core
nn/kernels and are re-exported at the reference paths; the fused layer
zoo (FusedLinear/FusedFeedForward/FusedBiasDropoutResidualLayerNorm/
FusedEcMoe/FusedDropoutAdd) wraps incubate.nn.functional."""
from ...nn.layers.transformer import (  # noqa: F401
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
    MultiHeadAttention as FusedMultiHeadAttention)
from ...kernels.flash_attention import (  # noqa: F401
    flash_attention as memory_efficient_attention)

from ...parallel.moe import MoELayer  # noqa: F401
from ..fused_multi_transformer import FusedMultiTransformer  # noqa: F401

from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedLinear, FusedDropoutAdd, FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward, FusedEcMoe)

__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer",
    "FusedLinear", "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe",
    "FusedDropoutAdd", "MoELayer", "memory_efficient_attention",
    "functional",
]
