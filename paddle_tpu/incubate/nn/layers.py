"""incubate.nn fused layers (reference
python/paddle/incubate/nn/layer/fused_linear.py:20,
fused_transformer.py:498 (FusedFeedForward), :379
(FusedBiasDropoutResidualLayerNorm), fused_ec_moe.py:20,
fused_dropout_add.py:20) — thin Layer wrappers over the functional
surface; XLA does the fusing."""
from __future__ import annotations

from ...nn.layer import Layer
from . import functional as FF


class FusedLinear(Layer):
    """reference incubate/nn/layer/fused_linear.py FusedLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter((out_features,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, input):
        return FF.fused_linear(input, self.weight, self.bias,
                               transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """reference incubate/nn/layer/fused_dropout_add.py FusedDropoutAdd."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return FF.fused_dropout_add(x, y, p=self.p,
                                    training=self.training,
                                    mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference incubate/nn/layer/fused_transformer.py:379."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=self._ones)
        self.ln_bias = self.create_parameter(
            (embed_dim,), is_bias=True)

    @staticmethod
    def _ones(shape, dtype):
        import jax.numpy as jnp
        return jnp.ones(shape, dtype)

    def forward(self, x, residual):
        return FF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    """reference incubate/nn/layer/fused_transformer.py:498."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._act_dropout = (dropout_rate if act_dropout_rate is None
                             else act_dropout_rate)
        self._act = activation
        self._epsilon = epsilon
        self._pre_ln = normalize_before
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True)
        import jax.numpy as jnp
        ones = lambda s, d: jnp.ones(s, d)  # noqa: E731
        self.ln1_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr, default_initializer=ones)
        self.ln1_bias = self.create_parameter(
            (d_model,), attr=ln1_bias_attr, is_bias=True)
        self.ln2_scale = self.create_parameter(
            (d_model,), attr=ln2_scale_attr, default_initializer=ones)
        self.ln2_bias = self.create_parameter(
            (d_model,), attr=ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        return FF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias,
            linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout,
            dropout2_rate=self._dropout_rate,
            activation=self._act, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon, pre_layer_norm=self._pre_ln,
            training=self.training)


class FusedEcMoe(Layer):
    """reference incubate/nn/layer/fused_ec_moe.py FusedEcMoe —
    expert-choice MoE over dense batched matmuls."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError(f"unsupported act_type {act_type!r}")
        self._act = act_type
        self.bmm_weight0 = self.create_parameter(
            (num_experts, hidden_size, inter_size), attr=weight_attr)
        self.bmm_bias0 = self.create_parameter(
            (num_experts, 1, inter_size), attr=bias_attr, is_bias=True)
        self.bmm_weight1 = self.create_parameter(
            (num_experts, inter_size, hidden_size), attr=weight_attr)
        self.bmm_bias1 = self.create_parameter(
            (num_experts, 1, hidden_size), attr=bias_attr, is_bias=True)

    def forward(self, x, gate):
        return FF.fused_ec_moe(x, gate, self.bmm_weight0, self.bmm_bias0,
                               self.bmm_weight1, self.bmm_bias1,
                               self._act)
