"""incubate.nn.functional (reference
python/paddle/incubate/nn/functional/__init__.py — the fused-kernel
functional surface: fused_transformer.py:32,275,465,873,
fused_matmul_bias.py:21,72, fused_ec_moe.py:18,
fused_dropout_add.py:22).

TPU-native: each "fused op" is expressed as the plain composition and
left to XLA to fuse — on TPU the compiler's fusion of
matmul+bias+dropout+residual+LN is the fast path the reference's
hand-written CUDA kernels emulate. The flash-attention core routes
through paddle_tpu.kernels (Pallas on TPU)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...nn import functional as F

__all__ = [
    "fused_multi_head_attention", "fused_feedforward",
    "fused_multi_transformer", "fused_matmul_bias", "fused_linear",
    "fused_bias_dropout_residual_layer_norm", "fused_ec_moe",
    "fused_dropout_add",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """reference fused_matmul_bias.py:21 — matmul + bias epilogue (the
    cuBLASLt epilogue fusion; XLA fuses the same pattern)."""
    from ...ops.math import matmul
    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False,
                 name=None):
    """reference fused_matmul_bias.py:72."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_dropout_add(x, y, p=0.5, training=True,
                      mode="upscale_in_train", name=None):
    """reference fused_dropout_add.py:22 — dropout(x) + y in one
    epilogue."""
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """reference fused_transformer.py:275 —
    layer_norm(residual + dropout(x + bias))."""
    out = x if bias is None else x + bias
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    out = out + residual
    return F.layer_norm(out, out.shape[-1], weight=ln_scale,
                        bias=ln_bias, epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """reference fused_transformer.py:32 — the transformer FFN block:
    residual = x
    out = LN1(x) if pre_layer_norm else x
    out = dropout2(linear2(dropout1(act(linear1(out)))))
    out = residual + out (if add_residual)
    out = LN2(out) if not pre_layer_norm."""
    residual = x
    out = x
    if pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], weight=ln1_scale,
                           bias=ln1_bias, epsilon=ln1_epsilon)
    out = fused_linear(out, linear1_weight, linear1_bias)
    act = getattr(F, activation)
    out = act(out)
    out = F.dropout(out, p=dropout1_rate, training=training, mode=mode)
    out = fused_linear(out, linear2_weight, linear2_bias)
    out = F.dropout(out, p=dropout2_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], weight=ln2_scale,
                           bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=-1, transpose_qkv_wb=False, name=None):
    """reference fused_transformer.py:465 — fused self-attention block.
    qkv_weight is the packed [3, num_heads, head_dim, embed_dim] tensor
    (or [embed_dim, 3*embed_dim] with transpose_qkv_wb=True); the
    attention core runs through the flash-attention kernel."""
    from ...ops.math import matmul
    from ...kernels.flash_attention import flash_attention

    residual = x
    out = x
    if pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], weight=pre_ln_scale,
                           bias=pre_ln_bias, epsilon=pre_ln_epsilon)

    B, S, D = out.shape
    wv = _v(qkv_weight)
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError(
                "transpose_qkv_wb=True requires num_heads")
        nh = num_heads
        qkv = matmul(out, qkv_weight)          # [B,S,3D]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkvv = _v(qkv).reshape(B, S, 3, nh, D // nh)
    else:
        # x [B,S,D] @ w [3,nh,hd,D] -> [B,S,3,nh,hd]
        qkvv = jnp.einsum("bsd,tnhd->bstnh", _v(out), wv)
        if qkv_bias is not None:
            qkvv = qkvv + _v(qkv_bias)[None, None]
    q, k, v = (qkvv[:, :, 0], qkvv[:, :, 1], qkvv[:, :, 2])  # [B,S,nh,hd]

    cache_kv_out = None
    if cache_kv is not None:
        ck, cv = _v(cache_kv[0]), _v(cache_kv[1])
        k = jnp.concatenate([ck, k], axis=1)
        v = jnp.concatenate([cv, v], axis=1)
        cache_kv_out = (Tensor(k), Tensor(v))

    # the reference op (fused_transformer.py:465) is NON-causal:
    # softmax(QK^T/sqrt(d) + mask) — causality, when wanted, arrives
    # via attn_mask
    drop = attn_dropout_rate if training else 0.0
    if attn_mask is None and drop == 0.0:
        ctx = _v(flash_attention(Tensor(q), Tensor(k), Tensor(v),
                                 causal=False))
    else:
        # masked / attention-dropout path: dense scores (the reference
        # kernel also materializes probs when a mask is supplied)
        scores = jnp.einsum("bsnh,btnh->bnst", q, k) / jnp.sqrt(
            jnp.asarray(q.shape[-1], q.dtype))
        if attn_mask is not None:
            scores = scores + _v(attn_mask)
        probs = jnp.exp(scores - jnp.max(scores, -1, keepdims=True))
        probs = probs / jnp.sum(probs, -1, keepdims=True)
        if drop > 0.0:
            probs = _v(F.dropout(Tensor(probs), p=drop, training=True))
        ctx = jnp.einsum("bnst,btnh->bsnh", probs, v)
    ctx = Tensor(ctx).reshape([B, S, -1])
    out = matmul(ctx, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], weight=ln_scale,
                           bias=ln_bias, epsilon=ln_epsilon)
    if cache_kv is not None:
        # reference: return (final_out, cache_kv_out) under decode
        return out, cache_kv_out
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases,
        linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
        ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
        pre_layer_norm=True, epsilon=1e-5, cache_kvs=None,
        pre_caches=None, seq_lens=None, rotary_embs=None, time_step=None,
        attn_mask=None, dropout_rate=0.0, rotary_emb_dims=0,
        activation="gelu", training=False, mode="upscale_in_train",
        trans_qkvw=True, ring_id=-1, name=None):
    """reference fused_transformer.py:873 — functional form of the
    decoder stack: per-layer weight LISTS are stacked on a leading axis
    and run through the same lax.scan core as the
    FusedMultiTransformer layer (one XLA computation for all layers).
    With trans_qkvw=True (the reference default), qkv weights arrive as
    [3*D, D] and are transposed into the stack's [D, 3*D] layout."""
    from ..fused_multi_transformer import _stack_forward
    if not pre_layer_norm:
        raise NotImplementedError(
            "fused_multi_transformer is pre-LN only (reference default)")
    if cache_kvs is not None:
        raise NotImplementedError(
            "functional fused_multi_transformer here serves the no-cache "
            "forward; use the FusedMultiTransformer layer for cached "
            "decode (it owns the stacked KV buffers)")

    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer here supports the reference default "
            "trans_qkvw=True layout ([3, num_heads, head_dim, "
            "embed_dim]) only")
    w0 = _v(qkv_weights[0])
    if w0.ndim != 4:
        raise ValueError(
            "qkv_weights must be the reference's [3, num_heads, "
            "head_dim, embed_dim] per-layer tensors (trans_qkvw=True "
            f"layout); got ndim={w0.ndim}")
    H, hd = w0.shape[1], w0.shape[2]

    def _stackl(ws):
        return jnp.stack([_v(w) for w in ws])

    # [3,H,hd,D] -> the scan core's [D, 3D] layout
    qkv_stack = jnp.stack([
        _v(w).reshape(3 * H * hd, w0.shape[3]).T for w in qkv_weights])
    pv = (_stackl(ln_scales), _stackl(ln_biases), qkv_stack,
          _stackl([jnp.reshape(_v(b), (-1,)) for b in qkv_biases]),
          _stackl(linear_weights), _stackl(linear_biases),
          _stackl(ffn_ln_scales), _stackl(ffn_ln_biases),
          _stackl(ffn1_weights), _stackl(ffn1_biases),
          _stackl(ffn2_weights), _stackl(ffn2_biases))
    pos = jnp.asarray(0, jnp.int32)
    bias = (_v(attn_mask).astype(jnp.float32)
            if attn_mask is not None else None)
    rot = None
    if rotary_embs is not None and rotary_emb_dims:
        from ..fused_multi_transformer import _rotary_tables
        rot = _rotary_tables(rotary_embs)
    out = _stack_forward(_v(x), None, None, pv, pos, H, hd, activation,
                         bias, rotary=rot,
                         rotary_dims=int(rotary_emb_dims))[0]
    return Tensor(out)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                 bmm1_bias, act_type):
    """reference fused_ec_moe.py:18 — gate-weighted dense mixture:
    out = sum_e softmax(gate)[..., e] * (act(x@W0_e + b0_e) @ W1_e
    + b1_e). x [B,S,D], gate [B,S,E], W0 [E,D,F], b0 [E,1,F],
    W1 [E,F,D], b1 [E,1,D]."""
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"unsupported act_type {act_type!r}")
    import jax
    xv, gv = _v(x), _v(gate)
    w0, b0 = _v(bmm0_weight), _v(bmm0_bias)
    w1, b1 = _v(bmm1_weight), _v(bmm1_bias)
    weights = jax.nn.softmax(gv, axis=-1)
    h = jnp.einsum("bsd,edf->bsef", xv, w0) + b0[None, :, 0]
    h = jnp.maximum(h, 0) if act_type == "relu" else jax.nn.gelu(
        h, approximate=False)       # erf gelu, same as F.gelu's default
    y = jnp.einsum("bsef,efd->bsed", h, w1) + b1[None, :, 0]
    return Tensor(jnp.einsum("bsed,bse->bsd", y, weights))
