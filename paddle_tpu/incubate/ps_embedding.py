"""Host-resident sparse embedding table — the parameter-server
sparse-table analog for beyond-HBM vocabularies.

Reference analog: the PS sparse table + trainer pull/push loop
(paddle/fluid/distributed/ps/table/memory_sparse_table.cc:1 hash-sharded
rows, ssd_sparse_table.cc:1 beyond-RAM spill, accessor SGD rules, entry
admission policies; trainer side paddle/fluid/framework/device_worker.h:266
DownpourWorker pull -> compute -> push). TPU-native collapse
(docs/ps_embedding_on_tpu.md): the multi-node brpc service becomes ONE
host-resident table beside the single-controller loop — `pull(ids)`
ships only the touched rows to device, the compiled step differentiates
w.r.t. those rows, and `push(ids, grads)` applies the update rule
host-side, exactly where the PS applied it server-side. In-HBM tables
(the default tier) are `parallel.mp_layers.VocabParallelEmbedding`; this
class is the spill tier.

Rows are allocated lazily in a grow-by-doubling arena keyed by feature
id (the memory_sparse_table hash-table semantics: ids are sparse,
unbounded, and mostly absent), with the reference's entry admission
policies honored: a `CountFilterEntry(k)` row reads as zeros and drops
updates until its id has been seen k times; `ProbabilityEntry(p)` gives
every sighting of an unadmitted id an independent admission draw at
probability p (memoryless, like the reference's creation attempts).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax.numpy as jnp


class HostShardedEmbedding:
    """Pull/push sparse embedding with host-side optimizer rules.

    optimizer: 'sgd' | 'adagrad' (the reference ctr accessor's naive and
    adagrad SGD rules).
    entry: parallel.dist_tail.CountFilterEntry / ProbabilityEntry / None.
    """

    def __init__(self, embedding_dim: int, lr: float = 0.05,
                 optimizer: str = "adagrad", entry=None,
                 init_scale: float = 0.01, seed: int = 0,
                 dtype=np.float32):
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError(
                f"optimizer {optimizer!r} is not one of sgd/adagrad")
        if entry is not None:
            from ..parallel.dist_tail import (CountFilterEntry,
                                              ProbabilityEntry)
            if not isinstance(entry, (CountFilterEntry,
                                      ProbabilityEntry)):
                raise ValueError(
                    f"entry {type(entry).__name__} is not an admission "
                    "policy this table understands (CountFilterEntry / "
                    "ProbabilityEntry; ShowClickEntry configures CTR "
                    "slot decay, which has no analog here)")
        self.dim = int(embedding_dim)
        self.lr = float(lr)
        self.optimizer = optimizer
        self.entry = entry
        self.init_scale = float(init_scale)
        self.dtype = np.dtype(dtype)
        self._rng = np.random.default_rng(seed)
        self._slot: Dict[int, int] = {}       # feature id -> arena row
        self._table = np.zeros((0, self.dim), self.dtype)
        self._accum = np.zeros((0, self.dim), np.float32)  # adagrad G
        self._seen: Dict[int, int] = {}       # admission counters
        self._size = 0

    # ------------------------------------------------------------ arena
    def _grow(self, need: int):
        cap = self._table.shape[0]
        if need <= cap:
            return
        new_cap = max(16, cap)
        while new_cap < need:
            new_cap *= 2
        pad = new_cap - cap
        self._table = np.concatenate(
            [self._table,
             np.zeros((pad, self.dim), self.dtype)], 0)
        self._accum = np.concatenate(
            [self._accum, np.zeros((pad, self.dim), np.float32)], 0)

    def _admit(self, fid: int) -> bool:
        """One sighting of `fid`; True when the row is (now) admitted."""
        if fid in self._slot:
            return True
        ent = self.entry
        name = type(ent).__name__ if ent is not None else ""
        if name == "CountFilterEntry":
            c = self._seen.get(fid, 0) + 1
            self._seen[fid] = c
            if c < ent._kw["count_filter"]:
                return False
        elif name == "ProbabilityEntry":
            # MEMORYLESS: every sighting of an unadmitted id gets a
            # fresh draw (the reference PS table keeps no rejection
            # state — a creation attempt either succeeds or leaves no
            # trace), so long-run admission probability for a feature
            # sighted k times is 1-(1-p)^k, not p. The old permanent
            # rejected-id memo could lock a frequent feature out of the
            # table forever on one unlucky draw.
            if self._rng.random() >= ent._kw["probability"]:
                return False
        self._grow(self._size + 1)
        self._slot[fid] = self._size
        self._table[self._size] = self._rng.normal(
            0.0, self.init_scale, (self.dim,)).astype(self.dtype)
        self._size += 1
        return True

    # -------------------------------------------------------- pull/push
    def pull(self, ids) -> jnp.ndarray:
        """[n] feature ids -> [n, dim] rows on device. Unadmitted ids
        read as zeros (reference entry semantics); each UNIQUE id counts
        one sighting per pull, and admission resolves before any row is
        read — duplicate ids in one batch always see the same value (the
        table holds one value per key, like the reference's)."""
        ids = np.asarray(ids).ravel()
        id_list = ids.tolist()
        admitted = {fid: self._admit(fid) for fid in dict.fromkeys(id_list)}
        out = np.zeros((ids.shape[0], self.dim), self.dtype)
        for i, fid in enumerate(id_list):
            if admitted[fid]:
                out[i] = self._table[self._slot[fid]]
        return jnp.asarray(out)

    def push(self, ids, grads):
        """Apply the update rule to the touched rows. Duplicate ids in
        the batch accumulate their gradients before ONE rule application
        (the reference merges by key before the table update)."""
        ids = np.asarray(ids).ravel()
        grads = np.asarray(grads).reshape(ids.shape[0], self.dim)
        merged: Dict[int, np.ndarray] = {}
        for i, fid in enumerate(ids.tolist()):
            if fid not in self._slot:
                continue                      # unadmitted: drop update
            if fid in merged:
                merged[fid] = merged[fid] + grads[i]
            else:
                merged[fid] = grads[i].astype(np.float32)
        if not merged:
            return
        rows = np.fromiter((self._slot[f] for f in merged), dtype=np.int64,
                           count=len(merged))
        g = np.stack(list(merged.values()))
        if self.optimizer == "adagrad":
            self._accum[rows] += g * g
            step = self.lr * g / (np.sqrt(self._accum[rows]) + 1e-10)
        else:
            step = self.lr * g
        self._table[rows] -= step.astype(self.dtype)

    # ------------------------------------------------------- inspection
    def __len__(self):
        return self._size

    def rows(self, ids) -> np.ndarray:
        """Host-side read (no admission side effects); zeros when
        absent."""
        ids = np.asarray(ids).ravel()
        out = np.zeros((ids.shape[0], self.dim), self.dtype)
        for i, fid in enumerate(ids.tolist()):
            slot = self._slot.get(fid)
            if slot is not None:
                out[i] = self._table[slot]
        return out

    # ------------------------------------------------------- save/load
    def state_dict(self) -> dict:
        ids = np.fromiter(self._slot.keys(), dtype=np.int64,
                          count=len(self._slot))
        rows = np.fromiter(self._slot.values(), dtype=np.int64,
                           count=len(self._slot))
        return {
            "ids": ids,
            "table": self._table[rows].copy(),
            "accum": self._accum[rows].copy(),
            "optimizer": self.optimizer,
            "lr": self.lr,
            "dim": self.dim,
        }

    def load_state_dict(self, state: dict):
        if int(state["dim"]) != self.dim:
            raise ValueError(
                f"checkpoint rows have dim {state['dim']}, table has "
                f"{self.dim}")
        if state.get("optimizer", self.optimizer) != self.optimizer:
            raise ValueError(
                f"checkpoint was trained with {state['optimizer']!r} "
                f"but this table applies {self.optimizer!r}; restoring "
                "it would silently change the update rule")
        n = state["ids"].shape[0]
        self._slot = {int(f): i for i, f in enumerate(state["ids"])}
        self._size = n
        self._table = np.asarray(state["table"], self.dtype).copy()
        self._accum = np.asarray(state["accum"], np.float32).copy()
        self._seen = {}
