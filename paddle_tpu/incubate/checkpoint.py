"""Auto-checkpoint: transparent epoch-range training snapshots.

Reference analog: python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py (TrainEpochRange:642 — iterate epochs under a context
that snapshots trainer state keyed by job id, so a restarted job resumes
from the last completed epoch instead of epoch 0; reference target was
HDFS, keyed by PADDLE_JOB_ID).

TPU-native shape: any object with state_dict/set_state_dict (Layer,
Optimizer, hapi Model, GradScaler) registers on the range; each completed
epoch atomically writes
    <dir>/<job_id>/<name>/epoch_<N>/
and construction restores the newest complete epoch, with the iterator
yielding only the REMAINING epochs. Works with the launch CLI's
restart-on-failure: the relaunched process resumes where the dead one
checkpointed.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Dict, Optional

def _save_dir() -> str:
    return os.environ.get("PADDLE_AUTO_CHECKPOINT_DIR",
                          os.path.join(".", "auto_checkpoint"))


def _job_id() -> str:
    return os.environ.get("PADDLE_JOB_ID", "default_job")


class TrainEpochRange:
    """for epoch in TrainEpochRange(90, "resnet-run"): ... train ...

    Register stateful objects before iterating:
        tr = TrainEpochRange(10, "run1")
        tr.add("model", model); tr.add("opt", opt)
    Each completed epoch checkpoints; a restarted process resumes."""

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_inter: int = 1, save_dir: Optional[str] = None):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name
        self.checkpoint_inter = max(1, int(checkpoint_inter))
        self._root = os.path.join(save_dir or _save_dir(), _job_id(), name)
        self._objects: Dict[str, object] = {}
        self._purge_stale_tmp()
        self._restored_epoch = self._find_latest()
        self._restored = False
        from ..parallel import get_world_size
        if get_world_size() > 1 and save_dir is None and \
                "PADDLE_AUTO_CHECKPOINT_DIR" not in os.environ:
            import warnings
            warnings.warn(
                "auto_checkpoint on a multi-process job needs a SHARED "
                "filesystem (set PADDLE_AUTO_CHECKPOINT_DIR): rank 0 "
                "writes the snapshots, and every rank must see them to "
                "agree on the resume epoch", RuntimeWarning)

    def _purge_stale_tmp(self):
        """Tmp dirs from crashed saves (pid-suffixed) leak one full
        snapshot per crash — exactly the jobs this feature serves. Only
        the WRITER rank purges, and only dirs that have been idle for a
        while: an elastic restart of one rank must never delete another
        live rank's in-progress save."""
        from ..parallel import get_rank
        if get_rank() != 0 or not os.path.isdir(self._root):
            return
        import time
        now = time.time()
        for d in os.listdir(self._root):
            if ".tmp" not in d:
                continue
            path = os.path.join(self._root, d)
            try:
                idle = now - os.path.getmtime(path)
            except OSError:
                continue
            if idle > 3600:
                shutil.rmtree(path, ignore_errors=True)

    # -- registration ------------------------------------------------------
    def add(self, name: str, obj):
        if not (hasattr(obj, "state_dict") and
                hasattr(obj, "set_state_dict")):
            raise TypeError(
                f"{name!r} must expose state_dict/set_state_dict")
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _meta_path(self, epoch):
        return os.path.join(self._root, f"epoch_{epoch}", "META.json")

    def _find_latest(self) -> int:
        """Newest COMPLETE epoch (META.json is written last), else -1."""
        if not os.path.isdir(self._root):
            return -1
        best = -1
        for d in os.listdir(self._root):
            if d.startswith("epoch_"):
                try:
                    e = int(d.split("_", 1)[1])
                except ValueError:
                    continue
                if e > best and os.path.exists(self._meta_path(e)):
                    best = e
        return best

    def _restore(self):
        self._restored = True
        if self._restored_epoch < 0:
            return
        from .. import framework_io
        base = os.path.join(self._root, f"epoch_{self._restored_epoch}")
        for name, obj in self._objects.items():
            path = os.path.join(base, f"{name}.pdparams")
            if not os.path.exists(path):
                # object added to the recipe after the checkpoint was
                # written: restore what exists, keep fresh state for the
                # rest (resume must not crash the job it exists to save)
                import warnings
                warnings.warn(
                    f"auto_checkpoint: no saved state for {name!r} in "
                    f"epoch_{self._restored_epoch}; keeping fresh init",
                    RuntimeWarning)
                continue
            obj.set_state_dict(framework_io.load(path))

    def save(self, epoch: int):
        # rank-0 writes, everyone else trusts it (multi-process launch:
        # ranks hold replicated state in SPMD); tmp dir is pid-unique so
        # a straggler from a dead process can't clobber a live writer
        from ..parallel import get_rank
        if get_rank() != 0:
            return
        from .. import framework_io
        base = os.path.join(self._root, f"epoch_{epoch}")
        tmp = base + f".tmp{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        for name, obj in self._objects.items():
            framework_io.save(obj.state_dict(),
                              os.path.join(tmp, f"{name}.pdparams"))
        with open(os.path.join(tmp, "META.json"), "w") as f:
            json.dump({"epoch": epoch, "name": self.name}, f)
        shutil.rmtree(base, ignore_errors=True)
        os.replace(tmp, base)
        # retire epochs older than one checkpoint interval (always at
        # least two complete checkpoints on disk)
        for d in os.listdir(self._root):
            if d.startswith("epoch_") and ".tmp" not in d:
                try:
                    e = int(d.split("_", 1)[1])
                except ValueError:
                    continue
                if e < epoch - self.checkpoint_inter:
                    shutil.rmtree(os.path.join(self._root, d),
                                  ignore_errors=True)

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if not self._restored:
            self._restore()
        for epoch in range(self._restored_epoch + 1, self.max_epoch_num):
            yield epoch
            if (epoch % self.checkpoint_inter == 0 or
                    epoch == self.max_epoch_num - 1):
                self.save(epoch)

    @property
    def restored_from_epoch(self) -> int:
        return self._restored_epoch


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, name="auto"):
    """Reference module-level helper auto_checkpoint.train_epoch_range."""
    return TrainEpochRange(max_epoch_num, name,
                           checkpoint_inter=save_checkpoint_inter)
