"""incubate.autograd (reference python/paddle/incubate/autograd/functional.py
vjp/jvp/Jacobian/Hessian — graduated: re-export of paddle_tpu.autograd)."""
from ..autograd.functional import (  # noqa: F401
    vjp, jvp, jacobian, hessian)

Jacobian = jacobian
Hessian = hessian


def enable_prim():
    """reference incubate/autograd/primapi enable_prim — switches the
    reference to primitive-op decomposition for higher-order autodiff.
    Decomposition IS the default here (every vjp is a jax primitive
    composition), so the switch records intent only."""
    _prim_state["enabled"] = True


def disable_prim():
    _prim_state["enabled"] = False


def prim_enabled():
    return _prim_state["enabled"]


_prim_state = {"enabled": True}


def forward_grad(outputs, inputs, grad_inputs=None):
    """reference primapi.py:25 — forward-mode JVP of outputs wrt
    inputs."""
    from ..autograd.functional import jvp as _jvp
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if callable(outputs):
        _, tangents = _jvp(outputs, ins, v=grad_inputs)
        return tangents
    raise NotImplementedError(
        "forward_grad needs the function form: pass a callable producing "
        "outputs (paddle_tpu.autograd.functional.jvp semantics); tape-"
        "recorded eager outputs support reverse mode via incubate."
        "autograd.grad")


def grad(outputs, inputs, grad_outputs=None):
    """reference primapi.py:108 — reverse-mode gradients; same contract
    as paddle.grad."""
    import paddle_tpu
    return paddle_tpu.grad(outputs, inputs, grad_outputs=grad_outputs)
