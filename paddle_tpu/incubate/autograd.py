"""incubate.autograd (reference python/paddle/incubate/autograd/functional.py
vjp/jvp/Jacobian/Hessian — graduated: re-export of paddle_tpu.autograd)."""
from ..autograd.functional import (  # noqa: F401
    vjp, jvp, jacobian, hessian)

Jacobian = jacobian
Hessian = hessian
