"""paddle_tpu.incubate.asp — automatic structured (n:m) sparsity.

Reference analog: python/paddle/incubate/asp (prune_model computing 2:4
masks per supported layer, decorate() wrapping the optimizer so masks are
re-applied after every step, calculate_density, excluded-layer registry —
asp/asp.py + supported_layer_list.py).

TPU note: n:m sparsity is an Ampere tensor-core execution feature; the
MXU has no sparse mode, so here ASP is a *model sparsification workflow*
(train with masks → export a provably 2:4-sparse model) rather than a
speedup. The mask math is pure jax and runs on device.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

_excluded_layers: Dict[int, List[str]] = {}
_masks: Dict[str, jnp.ndarray] = {}


# ------------------------------------------------------------------ masks
def compute_mask_1d(weight, n: int = 2, m: int = 4):
    """n:m mask along the LAST axis: in every group of m consecutive
    elements keep the n largest |w| (reference asp/utils.py
    compute_valid_2d_patterns/get_mask_1d)."""
    w = jnp.asarray(weight)
    size = w.shape[-1]
    if size % m != 0:
        raise ValueError(f"last dim {size} not divisible by m={m}")
    g = w.reshape(w.shape[:-1] + (size // m, m))
    # rank within each group; keep the top-n magnitudes
    order = jnp.argsort(jnp.abs(g), axis=-1)          # ascending
    ranks = jnp.argsort(order, axis=-1)               # rank of each elem
    mask = (ranks >= (m - n)).astype(w.dtype)
    return mask.reshape(w.shape)


def compute_mask_2d_greedy(weight, n: int = 2, m: int = 4):
    """Greedy 2D variant: mask both the last axis in n:m groups AND
    approximately balance rows (reference get_mask_2d_greedy). Here: 1D
    masks computed on w and wᵀ, intersected where both agree, then
    repaired per-group to keep exactly n survivors by magnitude."""
    w = jnp.asarray(weight)
    if w.ndim != 2 or w.shape[0] % m or w.shape[1] % m:
        return compute_mask_1d(w, n, m)
    # favor elements that survive in both row- and column-group ranking
    row_mask = compute_mask_1d(w, n, m)
    col_mask = compute_mask_1d(w.T, n, m).T
    score = jnp.abs(w) * (1.0 + row_mask + col_mask)
    size = w.shape[-1]
    g = score.reshape(score.shape[:-1] + (size // m, m))
    order = jnp.argsort(g, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= (m - n)).astype(w.dtype)
    return mask.reshape(w.shape)


MASK_ALGOS = {
    "mask_1d": compute_mask_1d,
    "mask_2d_greedy": compute_mask_2d_greedy,
    "mask_2d_best": compute_mask_2d_greedy,   # greedy is the tractable best
}


def check_mask_1d(weight, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group of the last axis has ≤ (m-n) nonzeros
    masked out, i.e. ≥ m-n zeros... i.e. at most n nonzeros."""
    w = np.asarray(weight)
    if w.shape[-1] % m:
        return False
    g = (w.reshape(-1, m) != 0).sum(axis=-1)
    return bool((g <= n).all())


def calculate_density(tensor) -> float:
    w = np.asarray(tensor.numpy() if hasattr(tensor, "numpy") else tensor)
    return float((w != 0).sum() / max(1, w.size))


# ----------------------------------------------------------- layer registry
def set_excluded_layers(model, param_names: List[str]):
    """Skip these parameters in prune_model/decorate (reference
    asp.set_excluded_layers)."""
    _excluded_layers.setdefault(id(model), []).extend(param_names)


def reset_excluded_layers(model=None):
    if model is None:
        _excluded_layers.clear()
    else:
        _excluded_layers.pop(id(model), None)


def _prunable_params(model):
    excluded = set(_excluded_layers.get(id(model), []))
    out = []
    for name, p in model.named_parameters():
        if name in excluded:
            continue
        shape = tuple(p.shape)
        # the reference prunes FC/conv weight matrices, not biases/norms
        if len(shape) >= 2 and shape[-1] % 4 == 0:
            out.append((name, p))
    return out


# ------------------------------------------------------------- workflow
def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Compute and apply n:m masks to the model's prunable weights
    (reference asp.prune_model). Masks are remembered so a decorated
    optimizer keeps re-applying them each step."""
    algo = MASK_ALGOS[mask_algo]
    pruned = {}
    for name, p in _prunable_params(model):
        mask = algo(p._value.astype(jnp.float32), n, m).astype(p.dtype)
        p._value = (p._value * mask)
        if with_mask:
            # keyed by Parameter identity (the object persists across
            # steps — step() swaps p._value in place); a weakref
            # finalizer evicts the entry when the param is collected so a
            # reused id can never pick up a stale mask
            import weakref
            _masks[id(p)] = mask
            weakref.finalize(p, _masks.pop, id(p), None)
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step so the ASP masks are re-applied after every
    update (reference asp.decorate → OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step
    params = list(optimizer._parameter_list)

    def masked_step(*a, **kw):
        out = orig_step(*a, **kw)
        for p in params:
            mask = _masks.get(id(p))
            if mask is not None and mask.shape == tuple(p.shape):
                p._value = p._value * mask.astype(p.dtype)
        return out

    optimizer.step = masked_step
    optimizer._asp_decorated = True
    return optimizer
