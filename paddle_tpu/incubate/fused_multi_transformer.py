"""FusedMultiTransformer — the fused inference decoder stack.

Reference analog: python/paddle/incubate/nn/layer/fused_transformer.py:1022
(FusedMultiTransformer: N pre-LN transformer layers with fused QKV and a
[2, B, H, max_len, hd]-per-layer KV cache, driven by the inference
predictor's generation loop); the int8 serving variant is
paddle/fluid/operators/fused/fused_multi_transformer_int8_op.cu:1.

TPU-native: per-layer weights live STACKED on a leading axis and the
whole stack applies with lax.scan (O(1) compile depth — the "fused"
property here is one XLA computation for all layers, which is what the
reference's hand-fused CUDA kernels bought); the KV cache is one stacked
[L, B, max_len, H, hd] buffer per k/v updated via dynamic_update_slice,
exactly the models/gpt.py decode design, exposed at the reference's
class surface (Parameters, cache_kvs list, time_step).

weight_only_quant() converts the four weight families to int8 with
per-(layer, out-channel) scales: single-token decode is weight-HBM-bound,
so halving the weight bytes is the int8 win on TPU — the convert feeding
the dot fuses into the operand load, and XLA reads int8 from HBM.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from ..nn.parameter import Parameter


class FusedMultiTransformer(Layer):
    """forward(src [B,T,D], caches=None, time_step=None) →
    (out [B,T,D], caches). Pre-LN (normalize_before=True, the reference
    default and its only supported mode)."""

    def __init__(self, embed_dim: int, num_heads: int, dim_feedforward: int,
                 dropout_rate: float = 0.0, activation: str = "gelu",
                 normalize_before: bool = True, num_layers: int = 1,
                 nranks: int = 1, trans_qkvw: bool = True, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only (the reference "
                "default; post-LN was never supported there either)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        L, D, F = num_layers, embed_dim, dim_feedforward
        std = 0.02

        # draws ride the framework's seeded stream (paddle.seed), like
        # every other layer's initializer
        from ..framework.random import next_key

        def norm(shape, scale=std):
            return (jax.random.normal(next_key(), shape, jnp.float32)
                    * scale).astype(jnp.float32)

        self.ln_scales = Parameter(jnp.ones((L, D), jnp.float32))
        self.ln_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.qkv_weights = Parameter(norm((L, D, 3 * D)))
        self.qkv_biases = Parameter(jnp.zeros((L, 3 * D), jnp.float32))
        self.linear_weights = Parameter(
            norm((L, D, D), std / math.sqrt(2 * L)))
        self.linear_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.ffn_ln_scales = Parameter(jnp.ones((L, D), jnp.float32))
        self.ffn_ln_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.ffn1_weights = Parameter(norm((L, D, F)))
        self.ffn1_biases = Parameter(jnp.zeros((L, F), jnp.float32))
        self.ffn2_weights = Parameter(
            norm((L, F, D), std / math.sqrt(2 * L)))
        self.ffn2_biases = Parameter(jnp.zeros((L, D), jnp.float32))

    # -- weight-only int8 ---------------------------------------------------
    _W_NAMES = ("qkv_weights", "linear_weights", "ffn1_weights",
                "ffn2_weights")
    _PV_NAMES = ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                 "linear_weights", "linear_biases", "ffn_ln_scales",
                 "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                 "ffn2_weights", "ffn2_biases")
    _SCALE_NAMES = ("qkv_weight_scales", "linear_weight_scales",
                    "ffn1_weight_scales", "ffn2_weight_scales")

    def _scan_inputs(self):
        """The stacked tensors _stack_forward scans over, in order — the
        single source of the pv layout (forward and the decode bench both
        use it)."""
        names = self._PV_NAMES + (
            self._SCALE_NAMES if getattr(self, "_weight_only", False)
            else ())
        return [getattr(self, n) for n in names]

    def weight_only_quant(self):
        """Convert the four stacked weight families to int8 with
        per-(layer, out-channel) scales (reference
        fused_multi_transformer_int8_op.cu's weight path). Serving-only:
        the fp Parameters are replaced by int8 + scale buffers, so the
        layer no longer trains. Idempotent."""
        if getattr(self, "_weight_only", False):
            return self
        from ..quantization.int8 import quantize_weight
        for name in self._W_NAMES:
            w = np.asarray(getattr(self, name).numpy(), np.float32)
            # [L, in, out]: per-layer channel-wise abs-max over the
            # contraction axis (the shared quantize_weight recipe)
            per_layer = [quantize_weight(w[l], channel_axis=1)
                         for l in range(w.shape[0])]
            w_q = np.stack([q for q, _ in per_layer])
            # stored pre-divided so the dequant epilogue is one multiply
            scale = np.stack([s for _, s in per_layer]) / 127.0
            delattr(self, name)
            self.register_buffer(name, Tensor(jnp.asarray(w_q)))
            self.register_buffer(f"{name[:-1]}_scales",
                                 Tensor(jnp.asarray(scale)))
        self._weight_only = True
        return self

    def _adopt_weight_only_structure(self):
        """Reshape params into the int8-buffer layout (values overwritten
        by the incoming state_dict)."""
        for name in self._W_NAMES:
            w = getattr(self, name)
            L, _, out = w.shape
            delattr(self, name)
            self.register_buffer(name, Tensor(
                jnp.zeros(tuple(w.shape), jnp.int8)))
            self.register_buffer(f"{name[:-1]}_scales", Tensor(
                jnp.ones((L, out), jnp.float32)))
        self._weight_only = True

    def set_state_dict(self, state_dict, *args, **kwargs):
        """A quantized model's state_dict (int8 weights + *_weight_scales)
        restores into a FRESH layer: the structure converts first, so the
        int8 codes land in int8 buffers instead of being miscast into fp
        Parameters."""
        if ("qkv_weight_scales" in state_dict
                and not getattr(self, "_weight_only", False)):
            self._adopt_weight_only_structure()
        return super().set_state_dict(state_dict, *args, **kwargs)

    # -- cache --------------------------------------------------------------
    def gen_cache(self, batch: int, max_len: int):
        """→ [k_cache, v_cache], each [L, B, max_len, H, hd] (the
        reference returns per-layer [2, B, H, max_len, hd] tensors; here
        one stacked pair scans with the stacked weights)."""
        shape = (self.num_layers, batch, max_len, self.num_heads,
                 self.head_dim)
        return [Tensor(jnp.zeros(shape, jnp.float32)),
                Tensor(jnp.zeros(shape, jnp.float32))]

    # -- forward ------------------------------------------------------------
    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                rotary_embs=None, rotary_emb_dims=0):
        """attn_mask: [B, S] (1=real, 0=pad) or an additive [B, 1, T, S]
        bias, combined with the causal mask. time_step may be an int or a
        scalar Tensor; it traces as a dynamic index, so every decode step
        reuses ONE compiled computation. rotary_embs: the reference's
        [2, B, 1, S, head_dim] cos/sin table (rotary_emb_dims groups the
        head dim) applied to q/k in every layer."""
        from ..framework.dispatch import apply
        pvals = self._scan_inputs()
        act = self.activation
        H, hd = self.num_heads, self.head_dim
        rot_dims = int(rotary_emb_dims) if rotary_embs is not None else 0
        # config must live in the dispatch cache key: the closure bakes
        # H/hd/act, and two models sharing (L, D) would otherwise collide
        cfg = f"L{self.num_layers}_H{H}_hd{hd}_{act}" + \
            ("_w8" if getattr(self, "_weight_only", False) else "") + \
            (f"_rot{rot_dims}" if rot_dims else "")
        pos_t = Tensor(jnp.asarray(
            int(time_step) if time_step is not None else 0, jnp.int32))
        B = src.shape[0]
        S_kv = caches[0].shape[2] if caches is not None else src.shape[1]
        if attn_mask is None:
            bias = Tensor(jnp.zeros((B, 1, 1, S_kv), jnp.float32))
        else:
            av = attn_mask._value if isinstance(attn_mask, Tensor) \
                else jnp.asarray(attn_mask)
            if av.ndim == 2:                       # [B, S] keep-mask
                bias = Tensor(jnp.where(av[:, None, None, :] > 0,
                                        0.0, -1e30).astype(jnp.float32))
            else:                                  # additive bias
                bias = Tensor(av.astype(jnp.float32))

        rot = ()
        if rot_dims:
            if caches is None and time_step is not None:
                # without caches the stack always runs from position 0;
                # honoring time_step here would make the rotary slice
                # clamp silently past a full-length table
                raise ValueError(
                    "time_step requires caches; the no-cache forward "
                    "rotates from position 0")
            cos, sin = _rotary_tables(rotary_embs)
            # time_step is concrete here (int() above), so the real
            # bound is checkable at call time: the stack only reads
            # table positions [time_step, time_step+T) — a table sized
            # to the decode horizon with a larger-allocated cache is
            # fine; reading past the table is not (dynamic_slice would
            # clamp and rotate late tokens at wrong positions)
            end = (int(time_step) if time_step is not None else 0) \
                + src.shape[1]
            if cos.shape[1] < end:
                raise ValueError(
                    f"rotary_embs covers {cos.shape[1]} positions but "
                    f"this call reads up to position {end}")
            rot = (Tensor(cos), Tensor(sin))

        def _rotary_of(r):
            return (r[0], r[1]) if r else None

        if caches is None:
            def fn(x, pos, bias_, *rest, cfg_id=None):
                r, pv = rest[:len(rot)], rest[len(rot):]
                return _stack_forward(x, None, None, pv, pos, H, hd, act,
                                      bias_, rotary=_rotary_of(r),
                                      rotary_dims=rot_dims)[0]
            return apply("fused_multi_transformer", fn, src, pos_t, bias,
                         *rot, *pvals, cfg_id=cfg)
        out = apply(
            "fused_multi_transformer_cached",
            lambda x, pos, bias_, kc, vc, *rest, cfg_id=None:
                _stack_forward(x, kc, vc, rest[len(rot):], pos, H, hd,
                               act, bias_,
                               rotary=_rotary_of(rest[:len(rot)]),
                               rotary_dims=rot_dims),
            src, pos_t, bias, caches[0], caches[1], *rot, *pvals,
            cfg_id=cfg)
        y, kc, vc = out
        return y, [kc, vc]


def _mm(x, w, scale=None):
    """x @ w with optional weight-only dequant: int8 w upcasts into the
    dot (XLA fuses the convert into the operand load — HBM reads stay
    int8) and the per-out-channel scale applies as an epilogue."""
    y = jnp.einsum("btd,df->btf",
                   x, w.astype(x.dtype) if w.dtype == jnp.int8 else w)
    if scale is not None:
        y = y * scale
    return y


def _rotary_tables(rotary_embs):
    """Unpack the reference's [2, B, 1, S, hd] rotary_embs tensor into
    per-position (cos [B,S,hd], sin [B,S,hd]) f32 tables — the ONE home
    for this extraction (layer forward + functional entry share it)."""
    rv = rotary_embs._value if isinstance(rotary_embs, Tensor) \
        else jnp.asarray(rotary_embs)
    if rv.ndim != 5 or rv.shape[0] != 2 or rv.shape[2] != 1:
        # shape[2] must be the literal 1 of the reference layout — a
        # per-head [2, B, H, S, hd] table would otherwise silently
        # reduce to head 0's angles for every head
        raise ValueError(
            f"rotary_embs must be the reference's [2, B, 1, S, head_dim] "
            f"cos/sin table; got shape {tuple(rv.shape)}")
    return (rv[0, :, 0].astype(jnp.float32),
            rv[1, :, 0].astype(jnp.float32))


def _apply_rotary(x, cos, sin, dims):
    """Reference rotary (fused_multi_transformer_op.cu.h:1546
    RotrayKernel): the head dim splits into `dims` groups of
    last = hd/dims; within a group, out_left = l*cos - r*sin and
    out_right = r*cos + l*sin (rotate-half / GPT-NeoX form), with
    cos/sin indexed by the group's first half.

    x [B,T,H,hd]; cos/sin [B,T,hd] (the reference's [2,B,1,S,hd]
    rotary_embs viewed per position, already sliced to this call's T
    positions)."""
    B, T, Hn, hd_ = x.shape
    last = hd_ // dims
    half = last // 2
    xr = x.reshape(B, T, Hn, dims, last)
    left, right = xr[..., :half], xr[..., half:]
    # the kernel reads cos/sin at the group's FIRST-half offsets
    cs = cos.reshape(B, T, 1, dims, last)[..., :half]
    sn = sin.reshape(B, T, 1, dims, last)[..., :half]
    out_left = left * cs - right * sn
    out_right = right * cs + left * sn
    return jnp.concatenate([out_left, out_right],
                           axis=-1).reshape(B, T, Hn, hd_).astype(x.dtype)


def _stack_forward(x, kcache, vcache, pv, pos, H, hd, act, bias=None,
                   rotary=None, rotary_dims=1):
    # pv is already in scan order: 12 stacked tensors, +4 weight scales
    # when weight-only-quantized (block unpacks per-layer slices by count)
    B, T, D = x.shape
    act_fn = jax.nn.gelu if act == "gelu" else jax.nn.relu

    def _ln(h, s, b):
        hf = h.astype(jnp.float32)
        mu = jnp.mean(hf, -1, keepdims=True)
        var = jnp.mean(jnp.square(hf - mu), -1, keepdims=True)
        return ((hf - mu) * jax.lax.rsqrt(var + 1e-5) * s + b).astype(
            h.dtype)

    use_cache = kcache is not None
    scale = 1.0 / math.sqrt(hd)

    # cos/sin for THIS call's T positions are layer-invariant: slice ONCE
    # here, not inside the scan body (XLA won't reliably hoist a
    # loop-invariant dynamic_slice out of the compiled While loop)
    rot_t = None
    if rotary is not None:
        cos_full, sin_full = rotary
        S_table = cos_full.shape[1]
        # only positions [pos, pos+T) are ever read, so the table needs
        # to cover pos+T — NOT the whole cache capacity (a rotary table
        # sized to the decode horizon with a larger-allocated cache is a
        # valid call pattern). With a traced `pos` the end position is
        # unknowable at trace time; require the T floor and rely on the
        # caller keeping pos+T within the table (dynamic_slice clamps,
        # which would rotate late tokens with the last table positions).
        static_pos = isinstance(pos, int) or (
            hasattr(pos, "item") and not isinstance(pos, jax.core.Tracer)
            and getattr(pos, "ndim", 1) == 0)
        S_need = (int(pos) + T) if (use_cache and static_pos) \
            else T
        if S_table < S_need:
            # dynamic_slice would silently CLAMP the start index and
            # rotate late tokens with the wrong positions — fail loudly
            # at trace time instead
            raise ValueError(
                f"rotary_embs covers {S_table} positions but this call "
                f"reads up to position {S_need}")
        p0 = jnp.asarray(pos, jnp.int32).reshape(())
        zero = jnp.zeros((), jnp.int32)
        rot_t = (
            jax.lax.dynamic_slice(cos_full, (zero, p0, zero), (B, T, hd)),
            jax.lax.dynamic_slice(sin_full, (zero, p0, zero), (B, T, hd)))

    def block(h, layer):
        if use_cache:
            *ws, kc, vc = layer
        else:
            ws, kc, vc = list(layer), None, None
        (ls, lb, qw, qb, lw, lbias, fs, fb, f1w, f1b, f2w, f2b) = ws[:12]
        qkv_sc, lin_sc, f1_sc, f2_sc = (tuple(ws[12:16]) if len(ws) >= 16
                                        else (None,) * 4)
        a_in = _ln(h, ls, lb)
        qkv = _mm(a_in, qw, qkv_sc) + qb
        q, k_, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k_ = k_.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        if rot_t is not None:
            q = _apply_rotary(q, rot_t[0], rot_t[1], rotary_dims)
            k_ = _apply_rotary(k_, rot_t[0], rot_t[1], rotary_dims)
        if use_cache:
            # pos is a traced scalar: one compiled computation serves
            # every decode step (dynamic_update_slice takes traced starts)
            p0 = jnp.asarray(pos, jnp.int32).reshape(())
            zero = jnp.zeros((), jnp.int32)
            kc = jax.lax.dynamic_update_slice(kc, k_,
                                              (zero, p0, zero, zero))
            vc = jax.lax.dynamic_update_slice(vc, v,
                                              (zero, p0, zero, zero))
            kf, vf = kc, vc
            S = kc.shape[1]
            kvpos = jnp.arange(S)[None, :]
            qpos = jnp.asarray(pos) + jnp.arange(T)[:, None]
            mask = kvpos <= qpos
        else:
            kf, vf = k_, v
            S = T
            mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale,
                       kf.astype(jnp.float32))
        s = jnp.where(mask, s, -1e30)
        if bias is not None:
            s = s + bias                       # [B,1,1/T,S] additive
        p = jax.nn.softmax(s, axis=-1)
        ctx = jnp.einsum("bhts,bshd->bthd", p, vf.astype(jnp.float32))
        ctx = ctx.reshape(B, T, D).astype(h.dtype)
        a = _mm(ctx, lw, lin_sc) + lbias
        h = h + a
        m_in = _ln(h, fs, fb)
        m = _mm(m_in, f1w, f1_sc) + f1b
        m = act_fn(m)
        m = _mm(m, f2w, f2_sc) + f2b
        h = h + m
        if use_cache:
            return h, (kc, vc)
        return h, None

    xs = list(pv)

    if use_cache:
        def scan_fn(h, layer):
            h, caches = block(h, layer)
            return h, caches
        h, (kcs, vcs) = jax.lax.scan(scan_fn, x,
                                     tuple(xs + [kcache, vcache]))
        return h, kcs, vcs

    def scan_fn(h, layer):
        h, _ = block(h, layer)
        return h, None
    h, _ = jax.lax.scan(scan_fn, x, tuple(xs))
    return (h,)
