"""FusedMultiTransformer — the fused inference decoder stack.

Reference analog: python/paddle/incubate/nn/layer/fused_transformer.py:1022
(FusedMultiTransformer: N pre-LN transformer layers with fused QKV and a
[2, B, H, max_len, hd]-per-layer KV cache, driven by the inference
predictor's generation loop).

TPU-native: per-layer weights live STACKED on a leading axis and the
whole stack applies with lax.scan (O(1) compile depth — the "fused"
property here is one XLA computation for all layers, which is what the
reference's hand-fused CUDA kernels bought); the KV cache is one stacked
[L, B, max_len, H, hd] buffer per k/v updated via dynamic_update_slice,
exactly the models/gpt.py decode design, exposed at the reference's
class surface (Parameters, cache_kvs list, time_step).
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from ..nn.parameter import Parameter


class FusedMultiTransformer(Layer):
    """forward(src [B,T,D], caches=None, time_step=None) →
    (out [B,T,D], caches). Pre-LN (normalize_before=True, the reference
    default and its only supported mode)."""

    def __init__(self, embed_dim: int, num_heads: int, dim_feedforward: int,
                 dropout_rate: float = 0.0, activation: str = "gelu",
                 normalize_before: bool = True, num_layers: int = 1,
                 nranks: int = 1, trans_qkvw: bool = True, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only (the reference "
                "default; post-LN was never supported there either)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        L, D, F = num_layers, embed_dim, dim_feedforward
        std = 0.02

        # draws ride the framework's seeded stream (paddle.seed), like
        # every other layer's initializer
        from ..framework.random import next_key

        def norm(shape, scale=std):
            return (jax.random.normal(next_key(), shape, jnp.float32)
                    * scale).astype(jnp.float32)

        self.ln_scales = Parameter(jnp.ones((L, D), jnp.float32))
        self.ln_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.qkv_weights = Parameter(norm((L, D, 3 * D)))
        self.qkv_biases = Parameter(jnp.zeros((L, 3 * D), jnp.float32))
        self.linear_weights = Parameter(
            norm((L, D, D), std / math.sqrt(2 * L)))
        self.linear_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.ffn_ln_scales = Parameter(jnp.ones((L, D), jnp.float32))
        self.ffn_ln_biases = Parameter(jnp.zeros((L, D), jnp.float32))
        self.ffn1_weights = Parameter(norm((L, D, F)))
        self.ffn1_biases = Parameter(jnp.zeros((L, F), jnp.float32))
        self.ffn2_weights = Parameter(
            norm((L, F, D), std / math.sqrt(2 * L)))
        self.ffn2_biases = Parameter(jnp.zeros((L, D), jnp.float32))

    # -- cache --------------------------------------------------------------
    def gen_cache(self, batch: int, max_len: int):
        """→ [k_cache, v_cache], each [L, B, max_len, H, hd] (the
        reference returns per-layer [2, B, H, max_len, hd] tensors; here
        one stacked pair scans with the stacked weights)."""
        shape = (self.num_layers, batch, max_len, self.num_heads,
                 self.head_dim)
        return [Tensor(jnp.zeros(shape, jnp.float32)),
                Tensor(jnp.zeros(shape, jnp.float32))]

    # -- forward ------------------------------------------------------------
    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        """attn_mask: [B, S] (1=real, 0=pad) or an additive [B, 1, T, S]
        bias, combined with the causal mask. time_step may be an int or a
        scalar Tensor; it traces as a dynamic index, so every decode step
        reuses ONE compiled computation."""
        from ..framework.dispatch import apply
        pvals = [self.ln_scales, self.ln_biases, self.qkv_weights,
                 self.qkv_biases, self.linear_weights, self.linear_biases,
                 self.ffn_ln_scales, self.ffn_ln_biases,
                 self.ffn1_weights, self.ffn1_biases,
                 self.ffn2_weights, self.ffn2_biases]
        act = self.activation
        H, hd = self.num_heads, self.head_dim
        # config must live in the dispatch cache key: the closure bakes
        # H/hd/act, and two models sharing (L, D) would otherwise collide
        cfg = f"L{self.num_layers}_H{H}_hd{hd}_{act}"
        pos_t = Tensor(jnp.asarray(
            int(time_step) if time_step is not None else 0, jnp.int32))
        B = src.shape[0]
        S_kv = caches[0].shape[2] if caches is not None else src.shape[1]
        if attn_mask is None:
            bias = Tensor(jnp.zeros((B, 1, 1, S_kv), jnp.float32))
        else:
            av = attn_mask._value if isinstance(attn_mask, Tensor) \
                else jnp.asarray(attn_mask)
            if av.ndim == 2:                       # [B, S] keep-mask
                bias = Tensor(jnp.where(av[:, None, None, :] > 0,
                                        0.0, -1e30).astype(jnp.float32))
            else:                                  # additive bias
                bias = Tensor(av.astype(jnp.float32))

        if caches is None:
            def fn(x, pos, bias_, *pv, cfg_id=None):
                return _stack_forward(x, None, None, pv, pos, H, hd, act,
                                      bias_)[0]
            return apply("fused_multi_transformer", fn, src, pos_t, bias,
                         *pvals, cfg_id=cfg)
        out = apply(
            "fused_multi_transformer_cached",
            lambda x, pos, bias_, kc, vc, *pv, cfg_id=None:
                _stack_forward(x, kc, vc, pv, pos, H, hd, act, bias_),
            src, pos_t, bias, caches[0], caches[1], *pvals, cfg_id=cfg)
        y, kc, vc = out
        return y, [kc, vc]


def _stack_forward(x, kcache, vcache, pv, pos, H, hd, act, bias=None):
    (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_s, fln_b,
     f1_w, f1_b, f2_w, f2_b) = pv
    B, T, D = x.shape
    act_fn = jax.nn.gelu if act == "gelu" else jax.nn.relu

    def _ln(h, s, b):
        hf = h.astype(jnp.float32)
        mu = jnp.mean(hf, -1, keepdims=True)
        var = jnp.mean(jnp.square(hf - mu), -1, keepdims=True)
        return ((hf - mu) * jax.lax.rsqrt(var + 1e-5) * s + b).astype(
            h.dtype)

    use_cache = kcache is not None
    scale = 1.0 / math.sqrt(hd)

    def block(h, layer):
        if use_cache:
            (ls, lb, qw, qb, lw, lbias, fs, fb, f1w, f1b, f2w, f2b,
             kc, vc) = layer
        else:
            (ls, lb, qw, qb, lw, lbias, fs, fb, f1w, f1b, f2w, f2b) = layer
            kc = vc = None
        a_in = _ln(h, ls, lb)
        qkv = jnp.einsum("btd,df->btf", a_in, qw) + qb
        q, k_, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k_ = k_.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        if use_cache:
            # pos is a traced scalar: one compiled computation serves
            # every decode step (dynamic_update_slice takes traced starts)
            p0 = jnp.asarray(pos, jnp.int32).reshape(())
            zero = jnp.zeros((), jnp.int32)
            kc = jax.lax.dynamic_update_slice(kc, k_,
                                              (zero, p0, zero, zero))
            vc = jax.lax.dynamic_update_slice(vc, v,
                                              (zero, p0, zero, zero))
            kf, vf = kc, vc
            S = kc.shape[1]
            kvpos = jnp.arange(S)[None, :]
            qpos = jnp.asarray(pos) + jnp.arange(T)[:, None]
            mask = kvpos <= qpos
        else:
            kf, vf = k_, v
            S = T
            mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale,
                       kf.astype(jnp.float32))
        s = jnp.where(mask, s, -1e30)
        if bias is not None:
            s = s + bias                       # [B,1,1/T,S] additive
        p = jax.nn.softmax(s, axis=-1)
        ctx = jnp.einsum("bhts,bshd->bthd", p, vf.astype(jnp.float32))
        ctx = ctx.reshape(B, T, D).astype(h.dtype)
        a = jnp.einsum("btd,df->btf", ctx, lw) + lbias
        h = h + a
        m_in = _ln(h, fs, fb)
        m = jnp.einsum("btd,df->btf", m_in, f1w) + f1b
        m = act_fn(m)
        m = jnp.einsum("btf,fd->btd", m, f2w) + f2b
        h = h + m
        if use_cache:
            return h, (kc, vc)
        return h, None

    if use_cache:
        def scan_fn(h, layer):
            h, caches = block(h, layer)
            return h, caches
        h, (kcs, vcs) = jax.lax.scan(
            scan_fn, x, (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_s,
                         fln_b, f1_w, f1_b, f2_w, f2_b, kcache, vcache))
        return h, kcs, vcs

    def scan_fn(h, layer):
        h, _ = block(h, layer)
        return h, None
    h, _ = jax.lax.scan(scan_fn, x, (ln_s, ln_b, qkv_w, qkv_b, lin_w,
                                     lin_b, fln_s, fln_b, f1_w, f1_b,
                                     f2_w, f2_b))
    return (h,)
