// Native shared-memory SPSC ring — the DataLoader's zero-copy batch
// transport between worker processes and the trainer process.
//
// Reference analog: the C++ side of the reference's multiprocess DataLoader
// (paddle/fluid/operators/reader/ + core.LoDTensor shared-memory transport
// used by python/paddle/io/dataloader/dataloader_iter.py:358 and
// worker.py's _share_memory path). The reference moves batches between
// Python workers and the trainer over /dev/shm LoDTensors with a
// file-descriptor handshake; here the transport is one anonymous
// MAP_SHARED region created BEFORE fork (no /dev/shm names to leak, no fd
// passing) holding a fixed ring of slots plus a control block of
// process-shared POSIX semaphores.
//
// Design: single-producer / single-consumer per ring (the Python side
// gives each worker its own ring and round-robins reads, preserving batch
// order deterministically — no cross-worker contention, no reordering
// buffer). Producer and consumer each own one cursor; the semaphores carry
// the full/empty counts, so no mutex is needed and a blocked side sleeps
// in the kernel (sem_timedwait) instead of spinning.
//
// Messages larger than one slot span consecutive slots (SPSC FIFO makes
// spanning safe); the first chunk's header carries the total payload size
// so the consumer knows how many chunks to drain.
//
// Built at first use by paddle_tpu.io.shm_ring (g++ -O2 -shared -fPIC,
// cached by source hash); loaded via ctypes. C ABI only.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <semaphore.h>

namespace {

struct SlotHeader {
  uint64_t nbytes;     // payload bytes in this slot
  uint64_t total;      // total message bytes (set on first chunk)
  uint32_t first;      // 1 when this slot starts a message
  uint32_t _pad;
};

struct Control {
  uint32_t magic;
  uint32_t n_slots;
  uint64_t slot_bytes;
  sem_t sem_free;      // slots available to the producer
  sem_t sem_full;      // slots ready for the consumer
  std::atomic<uint64_t> head;  // producer cursor (absolute slot count)
  std::atomic<uint64_t> tail;  // consumer cursor
  std::atomic<uint32_t> producer_done;  // producer hangup flag
};

constexpr uint32_t kMagic = 0x52494e47;  // "RING"

inline Control* ctrl(void* mem) { return static_cast<Control*>(mem); }

inline SlotHeader* slot_hdr(void* mem, uint64_t idx) {
  Control* c = ctrl(mem);
  char* base = static_cast<char*>(mem) + sizeof(Control);
  return reinterpret_cast<SlotHeader*>(
      base + (idx % c->n_slots) * (sizeof(SlotHeader) + c->slot_bytes));
}

inline char* slot_data(void* mem, uint64_t idx) {
  return reinterpret_cast<char*>(slot_hdr(mem, idx)) + sizeof(SlotHeader);
}

int timed_wait(sem_t* sem, long timeout_ms) {
  if (timeout_ms < 0) {  // infinite
    while (sem_wait(sem) != 0) {
      if (errno != EINTR) return -1;
    }
    return 0;
  }
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec += 1; ts.tv_nsec -= 1000000000L; }
  while (sem_timedwait(sem, &ts) != 0) {
    if (errno == EINTR) continue;
    return -1;  // ETIMEDOUT or error
  }
  return 0;
}

}  // namespace

extern "C" {

// Total bytes the caller must mmap (MAP_SHARED) for a ring.
uint64_t ring_region_size(uint32_t n_slots, uint64_t slot_bytes) {
  return sizeof(Control) +
         static_cast<uint64_t>(n_slots) * (sizeof(SlotHeader) + slot_bytes);
}

// Initialize the control block in an already-mapped shared region.
// Call once, in the parent, BEFORE forking workers. Returns 0 on success.
int ring_init(void* mem, uint32_t n_slots, uint64_t slot_bytes) {
  if (mem == nullptr || n_slots == 0 || slot_bytes == 0) return -1;
  Control* c = ctrl(mem);
  std::memset(c, 0, sizeof(Control));
  c->n_slots = n_slots;
  c->slot_bytes = slot_bytes;
  if (sem_init(&c->sem_free, /*pshared=*/1, n_slots) != 0) return -2;
  if (sem_init(&c->sem_full, /*pshared=*/1, 0) != 0) return -2;
  c->head.store(0);
  c->tail.store(0);
  c->producer_done.store(0);
  c->magic = kMagic;
  return 0;
}

// ---- producer side -------------------------------------------------------

// Write one message (possibly spanning slots). Blocks until enough slots
// free up. Returns 0 on success, -1 timeout, -2 bad ring, -3 message can
// never fit (should not happen: spanning handles any size).
int ring_put(void* mem, const char* data, uint64_t nbytes, long timeout_ms) {
  Control* c = ctrl(mem);
  if (c->magic != kMagic) return -2;
  uint64_t sent = 0;
  int first = 1;
  do {
    if (timed_wait(&c->sem_free, timeout_ms) != 0) return -1;
    uint64_t idx = c->head.load(std::memory_order_relaxed);
    SlotHeader* h = slot_hdr(mem, idx);
    uint64_t chunk = nbytes - sent;
    if (chunk > c->slot_bytes) chunk = c->slot_bytes;
    h->nbytes = chunk;
    h->total = nbytes;
    h->first = first;
    if (chunk) std::memcpy(slot_data(mem, idx), data + sent, chunk);
    sent += chunk;
    first = 0;
    c->head.store(idx + 1, std::memory_order_release);
    sem_post(&c->sem_full);
  } while (sent < nbytes);
  return 0;
}

// Mark the producer as finished; a blocked/future consumer read returns -4.
void ring_close_producer(void* mem) {
  Control* c = ctrl(mem);
  c->producer_done.store(1, std::memory_order_release);
  sem_post(&c->sem_full);  // wake a blocked consumer
}

// ---- consumer side -------------------------------------------------------

// Peek the size of the next full message. Blocks for the first chunk.
// Returns total message bytes (>=0), -1 timeout, -2 bad ring, -4 producer
// closed and ring drained. Does NOT consume; call ring_get next.
int64_t ring_next_size(void* mem, long timeout_ms) {
  Control* c = ctrl(mem);
  if (c->magic != kMagic) return -2;
  for (;;) {
    if (timed_wait(&c->sem_full, timeout_ms) != 0) {
      if (c->producer_done.load(std::memory_order_acquire) &&
          c->tail.load() == c->head.load())
        return -4;
      return -1;
    }
    // the hangup post carries no data; re-check emptiness
    if (c->tail.load() == c->head.load()) {
      if (c->producer_done.load(std::memory_order_acquire)) return -4;
      continue;  // spurious
    }
    sem_post(&c->sem_full);  // undo the decrement: ring_get re-waits
    return static_cast<int64_t>(slot_hdr(mem, c->tail.load())->total);
  }
}

// Read one full message into out (caller sized it via ring_next_size).
// Returns bytes read, -1 timeout, -2 bad ring, -4 producer closed+drained.
int64_t ring_get(void* mem, char* out, uint64_t out_cap, long timeout_ms) {
  Control* c = ctrl(mem);
  if (c->magic != kMagic) return -2;
  uint64_t got = 0, total = 0;
  int first = 1;
  do {
    if (timed_wait(&c->sem_full, timeout_ms) != 0) {
      if (first && c->producer_done.load(std::memory_order_acquire) &&
          c->tail.load() == c->head.load())
        return -4;
      return -1;
    }
    uint64_t idx = c->tail.load(std::memory_order_relaxed);
    if (idx == c->head.load(std::memory_order_acquire)) {
      // hangup wakeup with no data
      if (c->producer_done.load(std::memory_order_acquire) && first)
        return -4;
      continue;
    }
    SlotHeader* h = slot_hdr(mem, idx);
    if (first) {
      total = h->total;
      if (total > out_cap) return -3;
      first = 0;
    }
    if (h->nbytes) std::memcpy(out + got, slot_data(mem, idx), h->nbytes);
    got += h->nbytes;
    c->tail.store(idx + 1, std::memory_order_release);
    sem_post(&c->sem_free);
  } while (got < total);
  return static_cast<int64_t>(got);
}

// Introspection for tests: messages currently buffered (full slots).
int ring_full_slots(void* mem) {
  Control* c = ctrl(mem);
  if (c->magic != kMagic) return -2;
  int v = 0;
  sem_getvalue(&c->sem_full, &v);
  return v;
}

int ring_producer_done(void* mem) {
  return static_cast<int>(ctrl(mem)->producer_done.load());
}

}  // extern "C"
