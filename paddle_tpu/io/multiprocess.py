"""Multiprocess DataLoader workers over the native shared-memory ring.

Reference analog: python/paddle/io/dataloader/dataloader_iter.py:358
(_DataLoaderIterMultiProcess) + worker.py (_worker_loop, WorkerInfo) +
the C++ shared-memory LoDTensor transport. TPU-native shape of the same
idea: W forked worker processes each own one SPSC shm ring
(io/shm_ring.py, native C++); batch k is produced by worker k % W and the
trainer round-robins the rings, so batch order is deterministic and equal
to the single-process order — no reordering buffer, no cross-worker lock.

Workers must stay off the accelerator: the default collate here is a
numpy-only clone of io.default_collate_fn, and Tensor leaves coming out of
a custom collate_fn are converted to numpy before pickling (first jax use
in a forked child would re-enter the parent's TPU client). The trainer
side converts numpy leaves back to Tensor, so `num_workers=N` yields
exactly what `num_workers=0` yields.
"""
from __future__ import annotations

import os
import pickle
import signal
import sys
import traceback
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .shm_ring import ShmRing, RingClosed, RingTimeout

_worker_info = None


def worker_start_method() -> str:
    """How DataLoader workers are created. 'fork' (default, zero-copy:
    the dataset/collate cross into the child by address space, matching
    the reference's Linux default) or 'spawn' (PADDLE_TPU_WORKER_START=
    spawn): fresh processes that receive everything by pickle and attach
    the shm rings by name. Use spawn on multi-host jobs where the jax
    backend (and its thread pool) initializes before the first
    DataLoader — fork() in a thread-heavy process is a latent deadlock
    (jax emits the RuntimeWarning); spawn side-steps it at the cost of
    picklable datasets/collate_fns and slower worker startup."""
    m = os.environ.get("PADDLE_TPU_WORKER_START", "fork")
    if m not in ("fork", "spawn"):
        raise ValueError(
            f"PADDLE_TPU_WORKER_START={m!r} is not fork or spawn")
    return m


def _start_worker(target, args):
    """Start one worker by the configured method; returns its pid."""
    if worker_start_method() == "fork":
        pid = os.fork()
        if pid == 0:
            # child: never run parent atexit/finally frames
            try:
                target(*args)
            finally:
                os._exit(0)
        return pid
    import multiprocessing as mp
    proc = mp.get_context("spawn").Process(
        target=target, args=args, daemon=True)
    proc.start()
    return proc.pid


def _get_checked(ring, pid, timeout):
    """ring.get that survives a worker dying WITHOUT closing its ring
    (possible in spawn mode: the fresh interpreter can fail before the
    worker loop even starts — e.g. an unpicklable __main__, an import
    error). With no user timeout we poll and probe the pid so the parent
    raises instead of blocking forever; fork workers can't fail that
    way (the loop is entered in the already-running child) but the
    probe is harmless there."""
    if timeout is not None:
        return ring.get(timeout=timeout)
    while True:
        try:
            return ring.get(timeout=5.0)
        except RingTimeout:
            try:
                done, _ = os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                done = pid        # already reaped elsewhere: it IS dead
            if done:
                raise WorkerError(
                    f"DataLoader worker (pid {pid}) exited without "
                    "producing; with start_method=spawn check that the "
                    "dataset/collate_fn are picklable and importable "
                    "from the child") from None


class WorkerInfo:
    def __init__(self, id: int, num_workers: int, seed: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers}, "
                f"seed={self.seed})")


def get_worker_info():
    """Inside a worker process: this worker's WorkerInfo; else None.
    (reference python/paddle/io/dataloader/worker.py get_worker_info)"""
    return _worker_info


def np_collate(batch):
    """Numpy-only collate (same stacking rules as io.default_collate_fn,
    minus Tensor construction — that happens trainer-side)."""
    sample = batch[0]
    if hasattr(sample, "numpy") and callable(sample.numpy):
        # Tensor items (e.g. TensorDataset): pull to numpy in the worker —
        # mirrors default_collate_fn's Tensor branch so num_workers=N
        # stacks to one [B,...] batch exactly like num_workers=0
        return np.stack([np.asarray(s.numpy()) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, np.floating):
        return np.asarray(batch, sample.dtype)
    if isinstance(sample, float):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return [np_collate(list(g)) for g in zip(*batch)]
    if isinstance(sample, dict):
        return {k: np_collate([s[k] for s in batch]) for k in sample}
    return batch


def _to_numpy_tree(obj):
    if hasattr(obj, "numpy") and callable(obj.numpy):  # Tensor / jax.Array
        return np.asarray(obj.numpy())
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    return obj


def _to_tensor_tree(obj):
    from ..framework.tensor import to_tensor
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor_tree(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return obj


class _WorkerError:
    def __init__(self, worker_id: int, tb: str):
        self.worker_id = worker_id
        self.tb = tb


class _EpochEnd:
    """Data-ring marker a persistent worker emits after its last batch of
    an epoch (the ring stays open across epochs, so hangup can't signal)."""


class WorkerError(RuntimeError):
    pass


def _worker_loop(ring: ShmRing, worker_id: int, num_workers: int,
                 dataset, batch_indices: Optional[List[Sequence[int]]],
                 collate_fn, worker_init_fn, base_seed: int,
                 batch_size: Optional[int], drop_last: bool) -> None:
    """Child body. batch_indices=None → IterableDataset replica mode."""
    global _worker_info
    seed = base_seed + worker_id
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    np.random.seed(seed % (2 ** 32))
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        if batch_indices is None:
            import itertools
            it = iter(dataset)
            while True:
                batch = list(itertools.islice(it, batch_size))
                if not batch or (len(batch) < batch_size and drop_last):
                    break
                out = _to_numpy_tree(collate_fn(batch))
                ring.put(pickle.dumps(out, protocol=4))
        else:
            for j in range(worker_id, len(batch_indices), num_workers):
                items = [dataset[i] for i in batch_indices[j]]
                out = _to_numpy_tree(collate_fn(items))
                ring.put(pickle.dumps(out, protocol=4))
    except BaseException:
        try:
            err = _WorkerError(worker_id, traceback.format_exc())
            ring.put(pickle.dumps(err, protocol=4), timeout=10.0)
        except Exception:
            pass
    finally:
        ring.close_producer()


class MultiprocessIterator:
    """One epoch of batches produced by forked workers.

    Map-style: deterministic order — batch j comes from worker j % W.
    Iterable-style: each worker iterates its own dataset replica (split
    via get_worker_info, reference semantics); parent round-robins
    whichever rings still produce.
    """

    def __init__(self, dataset, batch_indices, collate_fn, num_workers,
                 prefetch_factor=2, timeout=0.0, worker_init_fn=None,
                 slot_bytes=1 << 22, batch_size=None, drop_last=False):
        self._timeout = None if not timeout else float(timeout)
        self._nw = num_workers
        self._batch_indices = batch_indices
        self._rings = [ShmRing(n_slots=max(2, prefetch_factor),
                               slot_bytes=slot_bytes)
                       for _ in range(num_workers)]
        self._pids: List[int] = []
        base_seed = int.from_bytes(os.urandom(4), "little")
        for w in range(num_workers):
            self._pids.append(_start_worker(
                _worker_loop,
                (self._rings[w], w, num_workers, dataset, batch_indices,
                 collate_fn, worker_init_fn, base_seed, batch_size,
                 drop_last)))

    def __iter__(self):
        try:
            if self._batch_indices is not None:
                # map-style, deterministic order: batch j IS worker j%W's
                # next message. Worker w owns exactly the global batches
                # ≡ w (mod W), so the first closed+drained ring proves no
                # batch at the current position exists — epoch over.
                j = 0
                while True:
                    try:
                        data = _get_checked(
                            self._rings[j % self._nw],
                            self._pids[j % self._nw], self._timeout)
                    except RingClosed:
                        break
                    except RingTimeout:
                        raise WorkerError(
                            f"DataLoader worker {j % self._nw} timed out "
                            f"after {self._timeout}s") from None
                    yield self._decode(j % self._nw, data)
                    j += 1
            else:
                # iterable-style: workers produce independent streams;
                # round-robin whatever is still open
                open_rings = list(range(self._nw))
                i = 0
                while open_rings:
                    w = open_rings[i % len(open_rings)]
                    try:
                        data = _get_checked(self._rings[w],
                                            self._pids[w], self._timeout)
                    except RingClosed:
                        open_rings.remove(w)
                        continue
                    except RingTimeout:
                        raise WorkerError(
                            f"DataLoader worker {w} timed out after "
                            f"{self._timeout}s") from None
                    yield self._decode(w, data)
                    i += 1
        finally:
            self.close()

    def _decode(self, w, data):
        obj = pickle.loads(data)
        if isinstance(obj, _WorkerError):
            raise WorkerError(
                f"DataLoader worker {obj.worker_id} failed:\n{obj.tb}")
        return obj

    def close(self):
        for pid in self._pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        for pid in self._pids:
            try:
                os.waitpid(pid, 0)
            except ChildProcessError:
                pass
        self._pids = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Persistent worker pool (reference persistent_workers=True: workers stay
# alive across epochs, dataloader_iter.py _try_shutdown_workers keep-alive
# path). Each worker gets a COMMAND ring (parent is the producer) carrying
# per-epoch work orders, and emits an _EpochEnd marker on its data ring
# after the epoch's last batch — the data ring never closes, so epoch
# boundaries are explicit messages instead of hangups.
# --------------------------------------------------------------------------
def _persistent_worker_loop(cmd_ring: ShmRing, data_ring: ShmRing,
                            worker_id: int, num_workers: int, dataset,
                            collate_fn, worker_init_fn, base_seed: int):
    global _worker_info
    seed = base_seed + worker_id
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    np.random.seed(seed % (2 ** 32))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    try:
        _persistent_epochs(cmd_ring, data_ring, dataset, collate_fn,
                           worker_id)
    finally:
        # parity with the one-shot _worker_loop: a dead/stopping worker
        # marks its ring closed so the parent gets RingClosed, never an
        # indefinite hang
        data_ring.close_producer()


def _persistent_epochs(cmd_ring, data_ring, dataset, collate_fn, worker_id):
    while True:
        try:
            cmd = pickle.loads(cmd_ring.get(timeout=None))
        except (RingClosed, Exception):
            return
        if cmd[0] == "stop":
            return
        kind, payload = cmd
        try:
            if kind == "epoch_map":
                for indices in payload:
                    items = [dataset[i] for i in indices]
                    out = _to_numpy_tree(collate_fn(items))
                    data_ring.put(pickle.dumps(out, protocol=4))
            elif kind == "epoch_iter":
                batch_size, drop_last = payload
                import itertools
                it = iter(dataset)
                while True:
                    batch = list(itertools.islice(it, batch_size))
                    if not batch or (len(batch) < batch_size and drop_last):
                        break
                    out = _to_numpy_tree(collate_fn(batch))
                    data_ring.put(pickle.dumps(out, protocol=4))
        except BaseException:
            import traceback as _tb
            try:
                data_ring.put(pickle.dumps(
                    _WorkerError(worker_id, _tb.format_exc()), protocol=4),
                    timeout=10.0)
            except Exception:
                pass
        data_ring.put(pickle.dumps(_EpochEnd(), protocol=4))


class PersistentWorkerPool:
    """Forked workers that survive across epochs. One pool per DataLoader
    when persistent_workers=True."""

    def __init__(self, dataset, collate_fn, num_workers, prefetch_factor=2,
                 timeout=0.0, worker_init_fn=None, slot_bytes=1 << 22):
        self._nw = num_workers
        self._timeout = None if not timeout else float(timeout)
        self._data_rings = [ShmRing(n_slots=max(2, prefetch_factor),
                                    slot_bytes=slot_bytes)
                            for _ in range(num_workers)]
        self._cmd_rings = [ShmRing(n_slots=4, slot_bytes=1 << 16)
                           for _ in range(num_workers)]
        self._pids: List[int] = []
        base_seed = int.from_bytes(os.urandom(4), "little")
        for w in range(num_workers):
            self._pids.append(_start_worker(
                _persistent_worker_loop,
                (self._cmd_rings[w], self._data_rings[w], w,
                 num_workers, dataset, collate_fn, worker_init_fn,
                 base_seed)))

    def run_epoch(self, batch_indices, batch_size=None, drop_last=False):
        """Yield one epoch's batches in deterministic order (map-style:
        batch j from worker j%W; iterable: round-robin until all workers
        end the epoch). An abandoned generator (early break) tears the
        pool down on exit — the rings hold an epoch nobody will consume,
        and respawning workers is cheaper than draining it; the
        DataLoader rebuilds the pool on the next epoch. Only ONE epoch
        may be in flight: the rings carry no epoch tags, so a second
        concurrent iterator would steal this one's batches."""
        if getattr(self, "_epoch_active", False):
            raise RuntimeError(
                "a persistent-workers DataLoader supports one in-flight "
                "iterator at a time (finish or abandon the previous epoch "
                "first, or use persistent_workers=False for concurrent "
                "iterators)")
        self._epoch_active = True
        ended = [False] * self._nw
        completed = False
        try:
            if batch_indices is not None:
                for w in range(self._nw):
                    sub = [batch_indices[j] for j in
                           range(w, len(batch_indices), self._nw)]
                    self._cmd_rings[w].put(pickle.dumps(("epoch_map",
                                                         sub)))
                for j in range(len(batch_indices)):
                    obj = self._get(j % self._nw)
                    if isinstance(obj, _EpochEnd):
                        ended[j % self._nw] = True
                        break
                    yield obj
                completed = True
            else:
                for w in range(self._nw):
                    self._cmd_rings[w].put(pickle.dumps(
                        ("epoch_iter", (batch_size, drop_last))))
                open_w = list(range(self._nw))
                i = 0
                while open_w:
                    w = open_w[i % len(open_w)]
                    obj = self._get(w)
                    if isinstance(obj, _EpochEnd):
                        ended[w] = True
                        open_w.remove(w)
                        continue
                    yield obj
                    i += 1
                completed = True
        finally:
            self._epoch_active = False
            if self._pids and completed:
                # normal completion: the end markers are already in the
                # rings (map-style never read them) — drain so the next
                # epoch starts clean; this is bounded and instant
                for w in range(self._nw):
                    while not ended[w]:
                        if isinstance(self._get(w), _EpochEnd):
                            ended[w] = True
            elif self._pids:
                # abandoned mid-epoch: don't block computing batches
                # nobody will read — respawn instead
                self.close()

    def _get(self, w):
        try:
            data = _get_checked(self._data_rings[w], self._pids[w],
                                self._timeout)
        except RingClosed:
            self.close()
            raise WorkerError(
                f"DataLoader worker {w} exited unexpectedly") from None
        except RingTimeout:
            self.close()       # undefined ring state: next epoch refreshes
            raise WorkerError(
                f"DataLoader worker {w} timed out after "
                f"{self._timeout}s") from None
        obj = pickle.loads(data)
        if isinstance(obj, _WorkerError):
            # in-flight batches/markers make the rings unusable: tear the
            # pool down; the DataLoader builds a fresh one next epoch
            self.close()
            raise WorkerError(
                f"DataLoader worker {obj.worker_id} failed:\n{obj.tb}")
        return obj

    def close(self):
        for w in range(self._nw):
            try:
                self._cmd_rings[w].put(pickle.dumps(("stop",)),
                                       timeout=1.0)
            except Exception:
                pass
        for pid in self._pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        for pid in self._pids:
            try:
                os.waitpid(pid, 0)
            except ChildProcessError:
                pass
        self._pids = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
