"""paddle_tpu.io — Dataset / DataLoader / samplers.

Reference analog: python/paddle/io/ (DataLoader with multiprocess workers +
shared-memory transfer, dataloader_iter.py:150,358). TPU-native: batches are
assembled host-side as numpy and land on device as one transfer; worker
parallelism uses a thread-pool prefetcher (the GIL is released inside numpy /
jax device_put, and XLA's async dispatch overlaps H2D with compute, which is
what the reference's shared-memory pipeline was buying).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Iterable, List, Optional

import numpy as np

from ..framework.tensor import Tensor, to_tensor
from ..framework import random as _random


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * total)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..parallel.env import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, np.int64))
    if isinstance(sample, np.floating):
        # np scalar items keep their precision (float64 targets stay f64);
        # without this branch a float32-item dataset collated to a raw list
        return to_tensor(np.asarray(batch, sample.dtype))
    if isinstance(sample, float):
        return to_tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(group)) for group in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._pool = None          # PersistentWorkerPool when persistent
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _multiprocess_ok(self) -> bool:
        """Multiprocess workers need the native shm ring (Linux + fork +
        g++) and use_shared_memory=True; anything else falls back to the
        thread prefetcher."""
        if self.num_workers <= 0 or not self.use_shared_memory:
            return False
        from .shm_ring import available
        return available()

    def _iter_multiprocess(self, bm):
        from .multiprocess import (MultiprocessIterator, np_collate,
                                   PersistentWorkerPool, _to_tensor_tree)
        if self._iterable_mode:
            batch_indices = None
        else:
            if self.batch_sampler is None:
                batch_indices = [[i] for i in range(len(self.dataset))]
            else:
                batch_indices = [list(ix) for ix in self.batch_sampler]
        # the worker must stay off the accelerator: the default collate
        # runs as its numpy clone there and Tensor assembly happens here
        user_collate = self.collate_fn is not default_collate_fn
        worker_collate = self.collate_fn if user_collate else np_collate
        if self.persistent_workers:
            # workers survive across epochs; per-epoch work orders go
            # over each worker's command ring. A pool torn down by a
            # worker error/timeout is rebuilt fresh.
            if self._pool is not None and not self._pool._pids:
                self._pool = None
            if self._pool is None:
                self._pool = PersistentWorkerPool(
                    self.dataset, worker_collate, self.num_workers,
                    prefetch_factor=self.prefetch_factor,
                    timeout=self.timeout,
                    worker_init_fn=self.worker_init_fn)
            gen = self._pool.run_epoch(
                batch_indices, batch_size=getattr(self, "batch_size", None),
                drop_last=getattr(self, "drop_last", False))
        else:
            gen = iter(MultiprocessIterator(
                self.dataset, batch_indices, worker_collate,
                self.num_workers, prefetch_factor=self.prefetch_factor,
                timeout=self.timeout, worker_init_fn=self.worker_init_fn,
                batch_size=getattr(self, "batch_size", None),
                drop_last=getattr(self, "drop_last", False)))
        while True:
            bm.before_reader()
            try:
                b = next(gen)
            except StopIteration:
                return
            bm.after_reader()
            yield _to_tensor_tree(b)

    def __iter__(self):
        # reader-cost hooks for the ips timer (reference: profiler/timer.py
        # Benchmark auto-attached to DataLoader)
        from ..profiler.timer import benchmark
        bm = benchmark()
        if self._multiprocess_ok():
            yield from self._iter_multiprocess(bm)
            return
        if self.num_workers == 0:
            it = self._batches()
            while True:
                bm.before_reader()
                try:
                    b = next(it)
                except StopIteration:
                    return
                bm.after_reader()
                yield b
        # thread prefetch pipeline
        q: "queue.Queue" = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            bm.before_reader()
            item = q.get()
            if item is sentinel:
                break          # sentinel pop is not a reader-cost sample
            bm.after_reader()
            yield item


def get_worker_info():
    """In a multiprocess DataLoader worker: that worker's WorkerInfo
    (id / num_workers / seed / dataset); None in the trainer process.
    (reference python/paddle/io/dataloader/worker.py get_worker_info)"""
    from .multiprocess import get_worker_info as _gwi
    return _gwi()
