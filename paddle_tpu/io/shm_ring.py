"""ctypes bindings for the native shared-memory ring (io/_native/shm_ring.cpp).

The ring is the DataLoader's worker→trainer batch transport (reference:
the shared-memory LoDTensor path of python/paddle/io/dataloader/worker.py +
dataloader_iter.py:358). One anonymous MAP_SHARED region per worker,
created before fork, holding a control block (process-shared POSIX
semaphores + SPSC cursors) and a fixed ring of slots; messages larger than
one slot span consecutive slots.

The .so is built from source on first use (g++ -O2 -shared -fPIC) into
paddle_tpu/io/_native/_build/, cached by source hash. `available()` is the
gate the DataLoader uses to fall back to the thread prefetcher when there
is no compiler or no Linux shm semantics.
"""
from __future__ import annotations

import ctypes
import os
import sys
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_native", "shm_ring.cpp")

_lib = None
_lib_err = None
_lock = threading.Lock()


def _build_lib():
    from ..utils.native_build import build_native_lib
    lib = build_native_lib(_SRC, "shm_ring", extra_flags=["-lpthread"])
    lib.ring_region_size.restype = ctypes.c_uint64
    lib.ring_region_size.argtypes = [ctypes.c_uint32, ctypes.c_uint64]
    lib.ring_init.restype = ctypes.c_int
    lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                              ctypes.c_uint64]
    lib.ring_put.restype = ctypes.c_int
    lib.ring_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64, ctypes.c_long]
    lib.ring_close_producer.restype = None
    lib.ring_close_producer.argtypes = [ctypes.c_void_p]
    lib.ring_next_size.restype = ctypes.c_int64
    lib.ring_next_size.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ring_get.restype = ctypes.c_int64
    lib.ring_get.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_uint64, ctypes.c_long]
    lib.ring_full_slots.restype = ctypes.c_int
    lib.ring_full_slots.argtypes = [ctypes.c_void_p]
    lib.ring_producer_done.restype = ctypes.c_int
    lib.ring_producer_done.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _lock:
        if _lib is None and _lib_err is None:
            try:
                _lib = _build_lib()
            except Exception as e:  # no g++, build error, exotic libc...
                _lib_err = e
    return _lib


def available() -> bool:
    """True when the native transport can be used (Linux + fork + g++)."""
    if sys.platform != "linux" or not hasattr(os, "fork"):
        return False
    return _get_lib() is not None


def unavailable_reason():
    if sys.platform != "linux":
        return f"platform {sys.platform} (need linux shm semantics)"
    return repr(_lib_err) if _lib_err else None


class RingTimeout(Exception):
    pass


class RingClosed(Exception):
    """Producer hung up and the ring is drained."""


class ShmRing:
    """SPSC shared-memory ring over a NAMED POSIX shm region.

    Fork-mode workers inherit the mapping; spawn-mode workers attach by
    name (the ring pickles as its name + geometry), which is what lets
    the DataLoader offer start_method='spawn' — the fork-after-jax-init
    deadlock escape hatch. The creating process owns the region and
    unlinks it on close()."""

    def __init__(self, n_slots: int = 4, slot_bytes: int = 1 << 22,
                 _attach: str | None = None):
        from multiprocessing import shared_memory
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(
                f"native shm ring unavailable: {unavailable_reason()}")
        self._lib = lib
        self.n_slots = int(n_slots)
        self.slot_bytes = int(slot_bytes)
        size = lib.ring_region_size(self.n_slots, self.slot_bytes)
        if _attach is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self._name = self._shm.name
            self._owner = True
            self._addr = ctypes.addressof(
                ctypes.c_char.from_buffer(self._shm.buf))
            rc = lib.ring_init(self._addr, self.n_slots, self.slot_bytes)
            if rc != 0:
                raise RuntimeError(f"ring_init failed (rc={rc})")
        else:
            # raw mmap of the named region: SharedMemory(name=...) would
            # enroll the attaching process with the resource tracker,
            # whose cleanup then races the owner's unlink (KeyError noise
            # / early unlink); the child needs only the mapping
            import mmap as _mmap
            fd = os.open(f"/dev/shm/{_attach}", os.O_RDWR)
            try:
                self._mm = _mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._shm = None
            self._name = _attach
            self._owner = False
            self._addr = ctypes.addressof(
                ctypes.c_char.from_buffer(self._mm))

    @property
    def name(self) -> str:
        return self._name

    # pickling = attach-by-name (spawn-mode workers)
    def __getstate__(self):
        return {"n_slots": self.n_slots, "slot_bytes": self.slot_bytes,
                "name": self._name}

    def __setstate__(self, state):
        self.__init__(state["n_slots"], state["slot_bytes"],
                      _attach=state["name"])

    def close(self):
        """Drop this process's mapping; the owner also unlinks the
        region. Idempotent."""
        shm = getattr(self, "_shm", None)
        mm = getattr(self, "_mm", None)
        self._shm = None
        self._mm = None
        self._addr = None
        if mm is not None:
            try:
                mm.close()
            except Exception:
                pass
        if shm is not None:
            try:
                shm.close()
            except Exception:
                pass
            if getattr(self, "_owner", False):
                try:
                    shm.unlink()
                except Exception:
                    pass

    def __del__(self):
        # named regions persist in /dev/shm until unlinked (anonymous
        # mmaps did not) — GC of the owner must reclaim them
        try:
            self.close()
        except Exception:
            pass

    def _live_addr(self):
        # a closed ring must fail as a Python error, not a NULL deref
        # inside the native code
        if self._addr is None:
            raise RingClosed("ring is closed")
        return self._addr

    # ---- producer ----
    def put(self, data, timeout: float | None = None) -> None:
        data = bytes(data) if not isinstance(data, (bytes, bytearray)) \
            else data
        t_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        rc = self._lib.ring_put(self._live_addr(), bytes(data), len(data),
                                t_ms)
        if rc == -1:
            raise RingTimeout(f"ring_put timed out after {timeout}s")
        if rc != 0:
            raise RuntimeError(f"ring_put failed (rc={rc})")

    def close_producer(self) -> None:
        self._lib.ring_close_producer(self._live_addr())

    # ---- consumer ----
    def get(self, timeout: float | None = None) -> bytes:
        t_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        size = self._lib.ring_next_size(self._live_addr(), t_ms)
        if size == -4:
            raise RingClosed
        if size == -1:
            raise RingTimeout(f"ring_get timed out after {timeout}s")
        if size < 0:
            raise RuntimeError(f"ring_next_size failed (rc={size})")
        buf = ctypes.create_string_buffer(int(size))
        got = self._lib.ring_get(self._live_addr(), buf, int(size), t_ms)
        if got == -4:
            raise RingClosed
        if got == -1:
            raise RingTimeout(f"ring_get timed out after {timeout}s")
        if got < 0:
            raise RuntimeError(f"ring_get failed (rc={got})")
        return buf.raw[:got]

    # ---- introspection ----
    def buffered(self) -> int:
        return max(0, self._lib.ring_full_slots(self._live_addr()))

    def producer_done(self) -> bool:
        return bool(self._lib.ring_producer_done(self._live_addr()))
