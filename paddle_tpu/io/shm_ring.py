"""ctypes bindings for the native shared-memory ring (io/_native/shm_ring.cpp).

The ring is the DataLoader's worker→trainer batch transport (reference:
the shared-memory LoDTensor path of python/paddle/io/dataloader/worker.py +
dataloader_iter.py:358). One anonymous MAP_SHARED region per worker,
created before fork, holding a control block (process-shared POSIX
semaphores + SPSC cursors) and a fixed ring of slots; messages larger than
one slot span consecutive slots.

The .so is built from source on first use (g++ -O2 -shared -fPIC) into
paddle_tpu/io/_native/_build/, cached by source hash. `available()` is the
gate the DataLoader uses to fall back to the thread prefetcher when there
is no compiler or no Linux shm semantics.
"""
from __future__ import annotations

import ctypes
import mmap
import os
import sys
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_native", "shm_ring.cpp")

_lib = None
_lib_err = None
_lock = threading.Lock()


def _build_lib():
    from ..utils.native_build import build_native_lib
    lib = build_native_lib(_SRC, "shm_ring", extra_flags=["-lpthread"])
    lib.ring_region_size.restype = ctypes.c_uint64
    lib.ring_region_size.argtypes = [ctypes.c_uint32, ctypes.c_uint64]
    lib.ring_init.restype = ctypes.c_int
    lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                              ctypes.c_uint64]
    lib.ring_put.restype = ctypes.c_int
    lib.ring_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64, ctypes.c_long]
    lib.ring_close_producer.restype = None
    lib.ring_close_producer.argtypes = [ctypes.c_void_p]
    lib.ring_next_size.restype = ctypes.c_int64
    lib.ring_next_size.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ring_get.restype = ctypes.c_int64
    lib.ring_get.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_uint64, ctypes.c_long]
    lib.ring_full_slots.restype = ctypes.c_int
    lib.ring_full_slots.argtypes = [ctypes.c_void_p]
    lib.ring_producer_done.restype = ctypes.c_int
    lib.ring_producer_done.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _lock:
        if _lib is None and _lib_err is None:
            try:
                _lib = _build_lib()
            except Exception as e:  # no g++, build error, exotic libc...
                _lib_err = e
    return _lib


def available() -> bool:
    """True when the native transport can be used (Linux + fork + g++)."""
    if sys.platform != "linux" or not hasattr(os, "fork"):
        return False
    return _get_lib() is not None


def unavailable_reason():
    if sys.platform != "linux":
        return f"platform {sys.platform} (need linux shm semantics)"
    return repr(_lib_err) if _lib_err else None


class RingTimeout(Exception):
    pass


class RingClosed(Exception):
    """Producer hung up and the ring is drained."""


class ShmRing:
    """SPSC shared-memory ring. Create in the parent BEFORE fork; both
    sides then use the same object (the mmap is inherited)."""

    def __init__(self, n_slots: int = 4, slot_bytes: int = 1 << 22):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(
                f"native shm ring unavailable: {unavailable_reason()}")
        self._lib = lib
        self.n_slots = int(n_slots)
        self.slot_bytes = int(slot_bytes)
        size = lib.ring_region_size(self.n_slots, self.slot_bytes)
        self._mm = mmap.mmap(-1, size)  # anonymous, MAP_SHARED
        self._addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        rc = lib.ring_init(self._addr, self.n_slots, self.slot_bytes)
        if rc != 0:
            raise RuntimeError(f"ring_init failed (rc={rc})")

    # ---- producer ----
    def put(self, data, timeout: float | None = None) -> None:
        data = bytes(data) if not isinstance(data, (bytes, bytearray)) \
            else data
        t_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        rc = self._lib.ring_put(self._addr, bytes(data), len(data), t_ms)
        if rc == -1:
            raise RingTimeout(f"ring_put timed out after {timeout}s")
        if rc != 0:
            raise RuntimeError(f"ring_put failed (rc={rc})")

    def close_producer(self) -> None:
        self._lib.ring_close_producer(self._addr)

    # ---- consumer ----
    def get(self, timeout: float | None = None) -> bytes:
        t_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        size = self._lib.ring_next_size(self._addr, t_ms)
        if size == -4:
            raise RingClosed
        if size == -1:
            raise RingTimeout(f"ring_get timed out after {timeout}s")
        if size < 0:
            raise RuntimeError(f"ring_next_size failed (rc={size})")
        buf = ctypes.create_string_buffer(int(size))
        got = self._lib.ring_get(self._addr, buf, int(size), t_ms)
        if got == -4:
            raise RingClosed
        if got == -1:
            raise RingTimeout(f"ring_get timed out after {timeout}s")
        if got < 0:
            raise RuntimeError(f"ring_get failed (rc={got})")
        return buf.raw[:got]

    # ---- introspection ----
    def buffered(self) -> int:
        return max(0, self._lib.ring_full_slots(self._addr))

    def producer_done(self) -> bool:
        return bool(self._lib.ring_producer_done(self._addr))
