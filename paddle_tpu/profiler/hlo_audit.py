"""GSPMD collective audit: what the compiled train step REALLY moves.

Reference analog: the auto_parallel cost-model validation pass
(python/paddle/distributed/auto_parallel/static/cost/base_cost.py
pricing comm ops op-by-op over the lowered program) + the profiler's
distributed view. TPU-native collapse: GSPMD inserts the collectives
during XLA SPMD partitioning, BELOW the StableHLO the jax tracer emits
(`pir.get_stablehlo` shows sharding annotations, not collectives) — so
the audit lowers the ACTUAL sharded step (`jax.jit(...).lower(...)
.compile().as_text()`, the same seam `profiler.cost_analysis` reads
its flop counts from) and parses the post-partitioning HLO for
all-gather / all-reduce / reduce-scatter / collective-permute /
all-to-all ops, sizing each from its result shape and mapping its
replica groups back onto the plan's mesh axes.

The diff against the plan's EXPECTED schedule is the product: a
dp×fsdp×tp plan should pay tp activation all-reduces, fsdp gathers/
scatters (or contraction all-reduces — GSPMD may choose either
spelling of ZeRO-3), and dp(×fsdp) gradient reductions. Anything else
— a collective-permute, an op on an axis combination no phase of the
cost_model.train_step_ledger prices — is a RESHARDING collective the
partitioner inserted involuntarily (XLA logs these as "Involuntary
full rematerialization"), i.e. a silent MFU killer, and surfaces as a
named audit finding instead of an unexplained slow step.

Static-count caveat: collectives inside a `while` (the stacked-layer
scan) appear ONCE in the HLO text but execute once per trip — counts
and bytes here are per-appearance, the schedule-shape signal, not a
wall-clock integral. Compile wall-ms and audit counts publish as
`train.compile.*` monitor stats next to the facade's `trace_count`.
"""
from __future__ import annotations

import itertools
import re
import time
from typing import Dict, List, Optional, Tuple

from . import monitor

COLLECTIVE_OPS = ("all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute", "all-to-all")

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

# `%name = <result-type> <op>(`; async forms appear as `<op>-start`
_OP_RE = re.compile(
    r"=\s+((?:\([^)]*\)|\S+))\s+(" + "|".join(COLLECTIVE_OPS)
    + r")(-start)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{} ]*\}\}|\[[\d,]+\]<=\[[\d,]+\]"
    r"(?:T\([\d,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")


def _type_bytes(type_str: str, async_start: bool = False) -> int:
    """Total bytes of an HLO result type (tuples summed). Async
    `<op>-start` ops return an (operands..., results...) tuple — count
    only the results half, or the same schedule would audit 2x the
    bytes of its sync spelling."""
    sizes = []
    for dt, dims in _SHAPE_RE.findall(type_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES[dt])
    if async_start and len(sizes) >= 2 and len(sizes) % 2 == 0:
        sizes = sizes[len(sizes) // 2:]
    return sum(sizes)


def _parse_groups(spec: str) -> List[Tuple[int, ...]]:
    """Replica groups from either HLO spelling: literal
    ``{{0,2},{1,3}}`` or iota ``[G,S]<=[dims]T(perm)`` (devices =
    arange(prod(dims)).reshape(dims).transpose(perm).reshape(G, S))."""
    if spec.startswith("{"):
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in re.findall(r"\{([\d, ]+)\}", spec[1:-1])
                if grp.strip()]
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", spec)
    if not m:
        return []
    import numpy as np
    out_dims = [int(x) for x in m.group(1).split(",")]
    src_dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(src_dims))).reshape(src_dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    ids = ids.reshape(out_dims)
    return [tuple(int(x) for x in row) for row in ids]


def _axis_groupings(mesh_axes: Dict[str, int]) -> Dict[frozenset, tuple]:
    """Map {frozenset of device-id groups -> mesh-axis combination}:
    for each axis subset, the groups that vary exactly those axes while
    fixing the rest (linear ids row-major over the mesh shape — jax's
    device order for a build_mesh mesh). Smallest subset wins when
    degree-1 axes make combinations degenerate."""
    import numpy as np
    names = [n for n in mesh_axes]
    sizes = [int(mesh_axes[n]) for n in names]
    ids = np.arange(int(np.prod(sizes))).reshape(sizes)
    out: Dict[frozenset, tuple] = {}
    idxs = [i for i, s in enumerate(sizes) if s > 1]
    for r in range(1, len(idxs) + 1):
        for combo in itertools.combinations(idxs, r):
            keep = [a for a in range(len(names)) if a not in combo]
            g = np.transpose(ids, keep + list(combo)).reshape(
                -1, int(np.prod([sizes[a] for a in combo])))
            key = frozenset(frozenset(int(x) for x in row) for row in g)
            out.setdefault(key, tuple(names[a] for a in combo))
    return out


def _pairs_axes(pairs: List[Tuple[int, int]],
                mesh_axes: Dict[str, int]) -> Optional[tuple]:
    """The mesh-axis combination a collective-permute's
    source_target_pairs vary (every pair's endpoints agree on all OTHER
    axis coordinates — e.g. the 1F1B stage-handoff ring varies exactly
    'pp'); None when the pairs cross axes inconsistently or ids fall
    outside the mesh."""
    import numpy as np
    if not pairs:
        return None
    names = list(mesh_axes)
    sizes = [int(mesh_axes[n]) for n in names]
    total = int(np.prod(sizes))
    if any(s >= total or t >= total for s, t in pairs):
        return None
    varying = set()
    for s, t in pairs:
        cs = np.unravel_index(s, sizes)
        ct = np.unravel_index(t, sizes)
        varying.update(i for i in range(len(names)) if cs[i] != ct[i])
    if not varying:
        return None
    return tuple(names[i] for i in sorted(varying))


def parse_hlo_collectives(hlo_text: str,
                          mesh_axes: Optional[Dict[str, int]] = None
                          ) -> List[dict]:
    """Every collective op in a post-partitioning HLO module text:
    ``{"op", "bytes", "count", "axes", "groups"}`` rows aggregated by
    (op, axes, group structure). `axes` is the mesh-axis combination
    the replica groups vary (None when they match no combination — a
    resharding group structure). collective-permutes carry
    source_target_pairs instead of replica_groups; their `axes` is the
    combination the pairs vary (`_pairs_axes`) — how the pp plan's
    expected stage-handoff ring is told apart from an involuntary
    resharding move."""
    groupings = _axis_groupings(mesh_axes) if mesh_axes else {}
    rows: Dict[tuple, dict] = {}
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        type_str, op = m.group(1), m.group(2)
        gm = _GROUPS_RE.search(line)
        groups = _parse_groups(gm.group(1)) if gm else []
        key_groups = frozenset(frozenset(g) for g in groups)
        axes = groupings.get(key_groups) if groups else None
        group_size = len(groups[0]) if groups else 0
        if axes is None and op == "collective-permute" and mesh_axes:
            pm = _PAIRS_RE.search(line)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in re.findall(r"\{(\d+,\d+)\}",
                                             pm.group(0))]
                axes = _pairs_axes(pairs, mesh_axes)
                group_size = group_size or 2
        # size-1 groups are partitioner no-ops (degree-1 axis residue)
        if groups and group_size <= 1:
            continue
        nbytes = _type_bytes(type_str, async_start=bool(m.group(3)))
        key = (op, axes, group_size)
        row = rows.setdefault(key, {
            "op": op, "axes": list(axes) if axes else None,
            "group_size": group_size, "count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += nbytes
    return sorted(rows.values(),
                  key=lambda r: (-r["bytes"], r["op"]))


def expected_collectives(plan) -> Dict[tuple, set]:
    """The op kinds a dp×fsdp×tp plan legitimately pays, per mesh-axis
    combination (the schedule cost_model.train_step_ledger prices):

    - tp: per-layer activation all-reduces (SP may spell them as a
      reduce-scatter + all-gather pair — same moved volume);
    - fsdp: ZeRO-3 parameter all-gathers + gradient reduce-scatters,
      OR contraction-dim partial-sum all-reduces (GSPMD picks per dot);
    - dp, and the combined dp×fsdp batch axes: gradient/loss
      reductions (all-reduce; reduce-scatter under sharded grads), and
      the batch all-gathers GSPMD inserts where a replicated value is
      rebuilt from batch-sharded shards;
    - pp (pp>1 plans only — the full-manual pipelined step of
      parallel/pipeline_train.py): the 1F1B stage-handoff
      collective-permute RING over the pp axis plus the output/loss
      broadcast all-reduce, and — because the manual step psums each
      gradient leaf over exactly the axes its spec does not name —
      all-reduces over EVERY combination of the live mesh axes.
    Everything NOT in this map — an involuntary resharding
    collective-permute above all — audits as a finding."""
    from ..cost_model import _plan_degrees
    deg = _plan_degrees(plan)
    exp: Dict[tuple, set] = {}
    if deg["tp"] > 1:
        exp[("tp",)] = {"all-reduce", "all-gather", "reduce-scatter"}
    if deg["fsdp"] > 1:
        exp[("fsdp",)] = {"all-gather", "reduce-scatter", "all-reduce"}
    if deg["dp"] > 1:
        exp[("dp",)] = {"all-reduce", "reduce-scatter", "all-gather"}
    batch = tuple(a for a in ("dp", "fsdp") if deg[a] > 1)
    if len(batch) > 1:
        exp[batch] = {"all-reduce", "reduce-scatter", "all-gather"}
    if deg.get("pp", 1) > 1:
        live = [a for a in ("dp", "fsdp", "tp", "pp") if deg[a] > 1]
        for r in range(1, len(live) + 1):
            for combo in itertools.combinations(live, r):
                exp.setdefault(combo, set()).add("all-reduce")
        exp.setdefault(("pp",), set()).add("collective-permute")
        if deg["tp"] > 1:
            # the qkv column re-gather + CE max gather, and their
            # reduce-scatter transposes
            exp[("tp",)] |= {"all-gather", "reduce-scatter"}
        if deg["fsdp"] > 1:
            exp[("fsdp",)] |= {"all-gather", "reduce-scatter"}
    return exp


def diff_vs_expected(collectives: List[dict], expected: Dict[tuple, set]
                     ) -> List[dict]:
    """Audit findings: every parsed collective whose (axes, op) the
    expected schedule does not cover, named by failure mode."""
    findings = []
    for row in collectives:
        axes = tuple(row["axes"]) if row["axes"] else None
        if axes is not None and row["op"] in expected.get(axes, ()):
            continue      # planned — incl. the pp stage-handoff ring
        if axes is None:
            findings.append(dict(
                row, kind="resharding_groups",
                detail="replica groups match no mesh-axis combination "
                       "— GSPMD resharding between layouts"))
        elif row["op"] == "collective-permute":
            findings.append(dict(
                row, kind="resharding_permute",
                detail=f"collective-permute over {axes} — a layout "
                       "move, not a planned schedule collective"))
        else:
            findings.append(dict(
                row, kind="unplanned_collective",
                detail=f"{row['op']} over {axes} is outside the plan's "
                       "expected schedule"))
    return findings


def audit_train_step(cfg, plan, global_batch: int, seq: int = 0,
                     family: str = "gpt", lr: float = 1e-3) -> dict:
    """Lower + compile the ACTUAL planner-driven GSPMD train step for
    (cfg, plan) over abstract avals (no params materialize) and audit
    the collectives GSPMD inserted against the plan's expected
    schedule. Returns {"plan", "counts", "collectives", "findings",
    "expected", "compile_ms", "n_devices"} and publishes
    `train.compile.audit_ms` / `train.compile.audits` monitor stats —
    the wall cost of auditing is itself observable."""
    import jax
    import jax.numpy as jnp
    from ..models import facade, gpt as gpt_mod, llama as llama_mod
    fam = {"gpt": gpt_mod, "llama": llama_mod}[family]
    seq = int(seq or cfg.max_seq_len)
    init = {"gpt": "init_gpt_params",
            "llama": "init_llama_params"}[family]
    params = jax.eval_shape(
        lambda k: getattr(fam, init)(cfg, k), jax.random.PRNGKey(0))
    opt = jax.eval_shape(gpt_mod.init_opt_state, params)
    toks = jax.ShapeDtypeStruct((int(global_batch), seq + 1), jnp.int32)
    mesh = plan.build_mesh()
    step = facade.make_train_step(fam.train_step, cfg=cfg, lr=lr,
                                  mesh=mesh, plan=plan)
    args = (params, opt, toks)
    step._build(args)
    t0 = time.perf_counter()
    compiled = step._jit.lower(*args).compile()
    compile_ms = (time.perf_counter() - t0) * 1e3
    text = compiled.as_text()
    mesh_axes = {str(a): int(s) for a, s in zip(mesh.axis_names,
                                                mesh.devices.shape)}
    collectives = parse_hlo_collectives(text, mesh_axes)
    expected = expected_collectives(plan)
    findings = diff_vs_expected(collectives, expected)
    counts: Dict[str, int] = {}
    for row in collectives:
        counts[row["op"]] = counts.get(row["op"], 0) + row["count"]
    monitor.gauge("train.compile.audit_ms").set(round(compile_ms, 3))
    monitor.counter("train.compile.audits").add()
    monitor.gauge("train.compile.audit_findings").set(len(findings))
    return {
        "plan": getattr(plan, "name", str(plan)),
        "n_devices": int(mesh.devices.size),
        "compile_ms": round(compile_ms, 1),
        "counts": counts,
        "collectives": collectives,
        "expected": {"+".join(k): sorted(v)
                     for k, v in expected.items()},
        "findings": findings,
    }
