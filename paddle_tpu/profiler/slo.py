"""SLO burn-rate monitoring for the serving fleet.

Reference analog: the fleet metrics the reference's serving/PS
deployments export through paddle/fluid/platform/monitor.h:1 registries
— raw counters an external alerting stack consumes. Here the alerting
half lives in-process: declared objectives over the serving SLO streams
(ServingEngine.export_slo_jsonl records, the finish-reason counters),
multi-window error-budget burn rates (the Google SRE workbook
multiwindow/multi-burn-rate pattern), and alert events that both
increment monitor counters (`slo.alerts`, `slo.alerts.<objective>`)
and trigger a flight-recorder dump (`slo_burn_alert`) so the black box
captures the window in which the budget burned.

Model:
- `Objective` declares what "bad" means for one stream:
  * kind="latency": a sample (ms) is bad when it exceeds
    `threshold_ms` — feed TTFT / inter-token samples;
  * kind="event": a request-level event is bad by construction —
    feed (bad, total) counts, e.g. poisoned/evicted/timeout finishes
    over completed requests, or router requeues over submissions.
  `budget` is the allowed bad fraction (the error budget), e.g. 0.001
  = 99.9% of samples must be good.
- `BurnRateMonitor` holds a timestamped sample log per objective and
  computes, for each (long, short) window pair, the burn rate
  bad_fraction / budget. An alert fires when BOTH windows of a pair
  burn at >= `alert_burn` (the long window filters blips, the short
  one guarantees the burn is CURRENT — the standard multiwindow
  argument), with a per-(objective, pair) cooldown so a sustained
  burn alerts once per cooldown, not once per check.

The clock is injectable (`clock=`) so tests and drills replay
histories deterministically; `check()` is pull-based — call it at any
cadence (the serving loop's natural one is alongside
`export_slo_jsonl`). tools/chaos_serving.py drills the alert → flight
dump path in its nan_logits and router_replica_death scenarios;
tools/telemetry_report.py's fleet mode renders the burn-rate summary.
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import monitor

__all__ = ["Objective", "Alert", "BurnRateMonitor", "DEFAULT_PAIRS"]

# (long_s, short_s) window pairs — serving-scale defaults (a fleet
# with hours-long budgets would pass SRE-workbook-scale pairs like
# (3600, 300), (21600, 1800))
DEFAULT_PAIRS: Tuple[Tuple[float, float], ...] = ((300.0, 30.0),
                                                  (60.0, 5.0))


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declared SLO. `name` keys the monitor counters and the
    report rows; `stream` names the sample stream fed to it (e.g.
    "ttft", "itl", "errors", "requeues")."""
    name: str
    stream: str
    kind: str = "latency"            # latency | event
    threshold_ms: float = 0.0        # latency: samples above are bad
    budget: float = 0.01             # allowed bad fraction

    def __post_init__(self):
        if self.kind not in ("latency", "event"):
            raise ValueError(f"kind {self.kind!r} (latency|event)")
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"budget must be in (0, 1]; "
                             f"got {self.budget}")


@dataclasses.dataclass(frozen=True)
class Alert:
    """One fired alert: the objective, the window pair that burned,
    and the burn rates that tripped it."""
    objective: str
    window_s: float
    short_window_s: float
    burn_rate: float
    short_burn_rate: float
    t: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class BurnRateMonitor:
    """Multi-window burn-rate evaluation over declared objectives.

    Consumers: the alerting path (check() -> flight dump) and the
    serving Autoscaler (inference/autoscale.py), which reads the
    short-window `burn_rate` per objective as a scale-out breach
    signal alongside fleet occupancy."""

    def __init__(self, objectives: Sequence[Objective],
                 pairs: Sequence[Tuple[float, float]] = DEFAULT_PAIRS,
                 alert_burn: float = 1.0,
                 cooldown_s: float = 60.0,
                 clock: Callable[[], float] = time.time,
                 max_samples: int = 65536):
        if not objectives:
            raise ValueError("BurnRateMonitor needs >= 1 objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        for long_s, short_s in pairs:
            if short_s >= long_s:
                raise ValueError(f"window pair ({long_s}, {short_s}): "
                                 "short must be < long")
        self.objectives = list(objectives)
        self.pairs = [(float(a), float(b)) for a, b in pairs]
        self.alert_burn = float(alert_burn)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        # per objective: deque of (t, bad_count, total_count) — latency
        # samples are (t, 0/1, 1), event feeds batch
        self._samples: Dict[str, collections.deque] = {
            o.name: collections.deque(maxlen=max_samples)
            for o in self.objectives}
        self._by_stream: Dict[str, List[Objective]] = {}
        for o in self.objectives:
            self._by_stream.setdefault(o.stream, []).append(o)
        self._last_alert: Dict[tuple, float] = {}
        self._m_alerts = monitor.counter("slo.alerts")
        self.alerts: List[Alert] = []        # full history, in order

    # ------------------------------------------------------------ feeding
    def observe_latency(self, stream: str, ms, t: Optional[float] = None
                        ) -> None:
        """One or many latency samples (ms) for `stream` ("ttft" /
        "itl" / any declared latency stream). Streams with no declared
        objective are ignored — feed unconditionally."""
        t = self.clock() if t is None else float(t)
        samples = [ms] if isinstance(ms, (int, float)) else list(ms)
        for obj in self._by_stream.get(stream, ()):
            if obj.kind != "latency":
                raise TypeError(f"objective {obj.name!r} is not a "
                                "latency objective")
            log = self._samples[obj.name]
            for v in samples:
                log.append((t, 1 if float(v) > obj.threshold_ms else 0,
                            1))

    def observe_events(self, stream: str, bad: int, total: int,
                       t: Optional[float] = None) -> None:
        """One batch of request-level events for an event objective:
        `bad` bad outcomes out of `total`."""
        t = self.clock() if t is None else float(t)
        for obj in self._by_stream.get(stream, ()):
            if obj.kind != "event":
                raise TypeError(f"objective {obj.name!r} is not an "
                                "event objective")
            self._samples[obj.name].append((t, int(bad), int(total)))

    def feed_slo_record(self, rec: dict) -> None:
        """Consume one `serving_slo` JSONL record
        (ServingEngine.export_slo_jsonl schema: raw ttft_ms / itl_ms
        sample lists, stamped `t`)."""
        t = rec.get("t")
        if rec.get("ttft_ms"):
            self.observe_latency("ttft", rec["ttft_ms"], t=t)
        if rec.get("itl_ms"):
            self.observe_latency("itl", rec["itl_ms"], t=t)

    # ----------------------------------------------------------- checking
    def burn_rate(self, objective: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """bad_fraction / budget over the trailing window (0.0 with no
        samples — an idle service burns no budget)."""
        now = self.clock() if now is None else float(now)
        obj = next(o for o in self.objectives if o.name == objective)
        bad = total = 0
        for t, b, n in self._samples[objective]:
            if t >= now - window_s:
                bad += b
                total += n
        if total == 0:
            return 0.0
        return (bad / total) / obj.budget

    def burn_rates(self, now: Optional[float] = None) -> dict:
        """objective -> {window_s: burn} over every distinct window
        (window keys rounded for stable JSON rendering)."""
        windows = sorted({w for pair in self.pairs for w in pair})
        return {o.name: {round(w, 1): round(
                            self.burn_rate(o.name, w, now), 3)
                         for w in windows}
                for o in self.objectives}

    def check(self, now: Optional[float] = None,
              flight: bool = True) -> List[Alert]:
        """Evaluate every (objective, window pair); fire alerts (both
        windows burning >= alert_burn, outside the pair's cooldown).
        Each alert increments `slo.alerts` + `slo.alerts.<objective>`
        and — with `flight` — leaves a `slo_burn_alert` flight dump
        carrying the burn rates (no-op without $PADDLE_TPU_FLIGHT_DIR,
        like every flight call)."""
        now = self.clock() if now is None else float(now)
        fired: List[Alert] = []
        for obj in self.objectives:
            for long_s, short_s in self.pairs:
                key = (obj.name, long_s, short_s)
                last = self._last_alert.get(key)
                if last is not None and now - last < self.cooldown_s:
                    continue
                long_burn = self.burn_rate(obj.name, long_s, now)
                if long_burn < self.alert_burn:
                    continue
                short_burn = self.burn_rate(obj.name, short_s, now)
                if short_burn < self.alert_burn:
                    continue
                self._last_alert[key] = now
                fired.append(Alert(obj.name, long_s, short_s,
                                   round(long_burn, 3),
                                   round(short_burn, 3), now))
        for alert in fired:
            self._m_alerts.add()
            monitor.counter(f"slo.alerts.{alert.objective}").add()
            monitor.gauge(f"slo.burn_rate.{alert.objective}").set(
                alert.burn_rate)
        if fired and flight:
            from . import flight_recorder
            rec = flight_recorder.recorder()
            rec.note(slo_burn_alerts=[a.to_dict() for a in fired])
            rec.configure(last_slo_alert=fired[-1].to_dict())
            rec.dump("slo_burn_alert")
        self.alerts.extend(fired)
        return fired

    # ------------------------------------------------------------ summary
    def summary(self, now: Optional[float] = None) -> dict:
        """The report block telemetry_report's fleet mode renders:
        per-objective burn rates per window + the alert history."""
        return {"objectives": [dataclasses.asdict(o)
                               for o in self.objectives],
                "burn_rates": self.burn_rates(now),
                "alerts": [a.to_dict() for a in self.alerts]}
