"""Batched step-metrics pipeline: in-jit scalars, one host pull per K steps.

Reference analog: the profiler/monitor export loops that stream scalar
training stats (python/paddle/profiler/profiler.py:340 stats pipeline +
the paddle/fluid/platform/monitor.h:1 registries the fleet trainers
publish into). The reference logs from host code; on this hardware that
is the one thing we cannot afford — a device->host pull costs 70-170 ms over
the TPU tunnel (CLAUDE.md), so per-step scalar logging would multiply
step time.

TPU-native design: the jitted step computes its scalars (loss, grad/
update global-norm, param global-norm, non-finite count, lr) into a
small `(every, n_fields)` float32 device accumulator that is DONATED
through the step like the params/opt buffers. The accumulator carries
its own int32 write cursor ON DEVICE, so recording needs no per-step
host->device step-index transfer either. Every `every` steps the host
pulls the whole block in ONE explicit `jax.device_get` (routed through
the `_host_pull` seam so tests can count transfers) and hands it to a
background JSONL writer thread — the step loop never blocks on JSON
encoding or disk.

The contract "zero extra host syncs between flush boundaries" is
enforced by tests/test_telemetry.py: the whole loop runs under
`jax.transfer_guard("disallow")` (explicit transfers — the flush — stay
legal; any implicit per-step pull or push trips the guard on backends
with real transfers) and the `_host_pull` seam must fire exactly
steps/every times.

JSONL schema (tools/telemetry_report.py is the consumer):
  {"kind": "run",     "t", "pid", "every", "fields", ...meta}
  {"kind": "step",    "step", <field>: float, ...}   # one per step
  {"kind": "flush",   "t", "step", "n"}              # one per pull
  {"kind": "monitor", "t", "pid", "stats": {...}}    # one per pull
  {"kind": "event",   "name", "t", "dur_s"}          # optional spans
"""
from __future__ import annotations

import json
import math
import os
import queue
import threading
import time
from typing import Dict, Optional, Sequence

from . import monitor

DEFAULT_FIELDS = ("loss", "grad_norm", "param_norm", "nonfinite", "lr")
# the MFU-observatory field set: + tokens trained per step, so the
# flush can turn flush-to-flush wall time into an achieved-MFU gauge
# (train.mfu) against the cost-model ledger's FLOPs/token
MFU_FIELDS = DEFAULT_FIELDS + ("tokens",)


# ------------------------------------------------------------ in-jit helpers
def global_norm(tree):
    """sqrt(sum of squares) over every inexact leaf — the grad/param
    global-norm scalar, computed in-jit."""
    import jax
    import jax.numpy as jnp
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            total += jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return jnp.sqrt(total)


def nonfinite_count(tree):
    """Number of non-finite elements across every inexact leaf (in-jit)."""
    import jax
    import jax.numpy as jnp
    total = jnp.zeros((), jnp.int32)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            total += jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
    return total


def grad_norm_from_moments(opt_old, opt_new, beta1=0.9, beta2=0.95):
    """Exact gradient global-norm recovered from an Adam-family moment
    update — the step functions in this repo return (loss, params',
    opt') without exposing grads, but the moments preserve them.

    Preferred path (opt state carries second moments under "v", as
    models.gpt.init_opt_state does): `new_v = b2*v + (1-b2)*g^2`, and
    the global norm only needs SUMS, which are linear —
    `sum(g^2) = (sum(new_v) - b2*sum(old_v)) / (1-b2)`. Crucially the
    old tree is consumed by a scalar reduction, not an elementwise
    combine with the new tree, so XLA can reduce-then-overwrite and the
    donated opt buffers stay donated (the elementwise first-moment
    recovery `g = (new_m - b1*m)/(1-b1)` needs both trees live at once
    — measured ~10% extra on the CPU bench rung vs ~0 for this form).

    Fallback (only "m" present): the elementwise recovery above, exact
    but donation-breaking. No moments at all -> nan."""
    import jax
    import jax.numpy as jnp
    if isinstance(opt_old, dict) and "v" in opt_old and "v" in opt_new:
        s_old = jnp.zeros((), jnp.float32)
        s_new = jnp.zeros((), jnp.float32)
        for lo, ln in zip(jax.tree_util.tree_leaves(opt_old["v"]),
                          jax.tree_util.tree_leaves(opt_new["v"])):
            s_old += jnp.sum(jnp.asarray(lo, jnp.float32))
            s_new += jnp.sum(jnp.asarray(ln, jnp.float32))
        sq = (s_new - beta2 * s_old) / (1.0 - beta2)
        return jnp.sqrt(jnp.maximum(sq, 0.0))
    m_old = opt_old.get("m") if isinstance(opt_old, dict) else None
    m_new = opt_new.get("m") if isinstance(opt_new, dict) else None
    if m_old is None or m_new is None:
        return jnp.asarray(jnp.nan, jnp.float32)
    total = jnp.zeros((), jnp.float32)
    for lo, ln in zip(jax.tree_util.tree_leaves(m_old),
                      jax.tree_util.tree_leaves(m_new)):
        g = (jnp.asarray(ln, jnp.float32) - beta1
             * jnp.asarray(lo, jnp.float32)) / (1.0 - beta1)
        total += jnp.sum(jnp.square(g))
    return jnp.sqrt(total)


# ------------------------------------------------------- host pull seam
def _host_pull(x):
    """THE device->host transfer of the pipeline — explicit, so it stays
    legal under `jax.transfer_guard("disallow")`. One seam so the
    flush-cadence test can count every pull the pipeline makes."""
    import jax
    return jax.device_get(x)


# ------------------------------------------------------- background writer
class TelemetryWriter:
    """Append-only JSONL writer draining a queue on a daemon thread, so
    flush boundaries enqueue host arrays and return without touching
    json.dumps or the filesystem."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="paddle-telemetry-writer", daemon=True)
        self._thread.start()

    def put(self, records) -> None:
        self._q.put(list(records))

    def _run(self) -> None:
        while True:
            recs = self._q.get()
            try:
                if recs is None:
                    return
                try:
                    with open(self.path, "a") as f:
                        for r in recs:
                            f.write(json.dumps(r) + "\n")
                except (OSError, TypeError, ValueError) as e:
                    # a full disk or unserializable record must not kill
                    # the drain thread (flush()/close() would then hang) —
                    # but the loss must be VISIBLE: counted in the monitor
                    # registry and reported once on stderr
                    n = monitor.counter("telemetry_write_errors").add()
                    if n == 1:
                        import sys
                        print(f"[telemetry] dropping records: {e}",
                              file=sys.stderr, flush=True)
            finally:
                self._q.task_done()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every enqueued record is on disk."""
        deadline = None if timeout is None else time.time() + timeout
        while not self._q.unfinished_tasks == 0:
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("telemetry writer did not drain")
            time.sleep(0.005)

    def close(self) -> None:
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=10)


# ------------------------------------------------------------- the pipeline
class TelemetryPipeline:
    """Owns the field layout, the device accumulator protocol, and the
    flush cadence.

    Usage (plain loop; `instrument_train_step` packages this for
    facade-style steps):

        tele = TelemetryPipeline(path, every=8)
        state = tele.device_init()
        @jax.jit                       # donate params/opt/state
        def step(params, opt, batch, tstate):
            ...
            tstate = tele.device_record(tstate, loss=loss,
                                        grad_norm=global_norm(grads))
            return loss_dev, new_params, new_opt, tstate
        for i in range(n):
            _, params, opt, state = step(params, opt, batch, state)
            state = tele.tick(i, state)    # ONE pull every `every` steps
        tele.close()
    """

    def __init__(self, path: str, every: int = 8,
                 fields: Sequence[str] = DEFAULT_FIELDS,
                 meta: Optional[dict] = None,
                 flops_per_token: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        if every < 1:
            raise ValueError("every must be >= 1")
        self.path = path
        self.every = int(every)
        self.fields = tuple(fields)
        # achieved-MFU wiring (docs/observability.md "Training
        # observability"): with `flops_per_token` (the cost-model
        # ledger's model_flops / tokens — cost_model.
        # train_flops_per_token) and `peak_flops` (TOTAL across the
        # plan's chips), each flush past the first (the compile window)
        # computes mfu = flops_per_token · tokens/s ÷ peak_flops from
        # the recorded `tokens` field and the flush-to-flush wall delta
        # — no extra pulls, no per-step clocks — and publishes the
        # `train.mfu` / `train.tokens_per_s` gauges into the same
        # monitor snapshot the flush already writes.
        if flops_per_token and "tokens" not in self.fields:
            raise ValueError(
                "flops_per_token= needs a 'tokens' field "
                "(fields=telemetry.MFU_FIELDS)")
        self._flops_per_token = flops_per_token
        self._peak_flops = peak_flops
        self._prev_flush_t: Optional[float] = None
        self._writer = TelemetryWriter(path)
        self._pulls = 0
        self._floor = 0        # lowest cursor value this process wrote
        header = {"kind": "run", "t": time.time(), "pid": os.getpid(),
                  "every": self.every, "fields": list(self.fields)}
        if meta:
            header.update(meta)
        self._writer.put([header])

    # ------------------------------------------------------------- device
    def device_init(self, start: int = 0):
        """Fresh accumulator: {"buf": (every, n_fields) f32 nan, "n": i32
        cursor}. `start` seeds the cursor so a resumed trainer's records
        continue from its restored step instead of colliding with the
        pre-crash process's ids in a shared JSONL."""
        import jax.numpy as jnp
        self._floor = int(start)
        return {"buf": jnp.full((self.every, len(self.fields)), jnp.nan,
                                jnp.float32),
                "n": jnp.full((), int(start), jnp.int32)}

    def device_record(self, tstate, **scalars):
        """In-jit: write one row at the device-side cursor and advance it.
        Unknown field names raise; missing fields record nan."""
        import jax
        import jax.numpy as jnp
        unknown = set(scalars) - set(self.fields)
        if unknown:
            raise ValueError(f"unknown telemetry fields {sorted(unknown)}; "
                             f"declared fields are {self.fields}")
        row = jnp.stack([
            jnp.asarray(scalars.get(f, jnp.nan), jnp.float32)
            for f in self.fields])
        idx = jnp.mod(tstate["n"], self.every)
        buf = jax.lax.dynamic_update_slice(tstate["buf"], row[None, :],
                                           (idx, 0))
        return {"buf": buf, "n": tstate["n"] + 1}

    # --------------------------------------------------------------- host
    def due(self, step: int) -> bool:
        """True when the host loop (0-based step just run) is at a flush
        boundary."""
        return (int(step) + 1) % self.every == 0

    def flush(self, tstate) -> None:
        """Pull the accumulator to host (ONE explicit transfer) and hand
        the block to the background writer."""
        host = _host_pull(tstate)
        self._pulls += 1
        self._enqueue(host)

    def _enqueue(self, host, count: Optional[int] = None) -> None:
        import numpy as np
        buf = np.asarray(host["buf"])
        n = int(host["n"])
        now = time.time()
        # rows [first, n) are valid BY CONSTRUCTION of the device cursor —
        # no in-band sentinel, so a step whose every field is nan (the
        # diverged step an operator most needs) is still emitted. The
        # floor clamp keeps a resume-seeded cursor (device_init(start=S)
        # with S % every != 0) from emitting the nan-filled slots below S
        # as phantom records on its first flush.
        first = max(self._floor,
                    n - (self.every if count is None else count))
        records = []
        for step in range(first, n):
            row = buf[step % self.every]
            rec = {"kind": "step", "step": step}
            for f, v in zip(self.fields, row):
                rec[f] = None if math.isnan(float(v)) else float(v)
            records.append(rec)
        records.append({"kind": "flush", "t": now, "step": n - 1,
                        "n": len(records)})
        # achieved MFU: from the SECOND flush on (the first window
        # absorbs the jit compile — telemetry_report's exclusion rule),
        # turn this window's recorded tokens + wall delta into the
        # train.mfu / train.tokens_per_s gauges. Gauges are set BEFORE
        # the snapshot below so the same flush's monitor record carries
        # them into the JSONL.
        if (self._flops_per_token and "tokens" in self.fields
                and self._prev_flush_t is not None
                and now > self._prev_flush_t):
            tok_i = self.fields.index("tokens")
            window_tokens = float(sum(
                0.0 if math.isnan(float(buf[s % self.every][tok_i]))
                else float(buf[s % self.every][tok_i])
                for s in range(first, n)))
            if window_tokens > 0:
                peak = self._peak_flops
                if not peak:
                    # the recorded tokens are GLOBAL, so the default
                    # denominator must be too: one ChipSpec peak per
                    # visible device (a single-chip fallback would
                    # overstate MFU by n_devices on a sharded run) —
                    # pass peak_flops= explicitly when the mesh spans a
                    # subset of the backend
                    import jax
                    from ..parallel.planner import ChipSpec
                    peak = self._peak_flops = (ChipSpec().peak_flops
                                               * jax.device_count())
                tps = window_tokens / (now - self._prev_flush_t)
                monitor.gauge("train.tokens_per_s").set(round(tps, 1))
                monitor.gauge("train.mfu").set(
                    round(self._flops_per_token * tps / peak, 6))
        self._prev_flush_t = now
        # live memory gauges ride the same flush (host-side PJRT /
        # proc reads, zero device pulls) so the monitor record below
        # carries hbm.bytes_in_use / hbm.peak_bytes into the JSONL
        from .mem_audit import publish_hbm_gauges
        publish_hbm_gauges()
        records.append({"kind": "monitor", "t": now, "pid": os.getpid(),
                        "stats": monitor.snapshot()})
        self._writer.put(records)

    def tick(self, step: int, tstate):
        """Per-step host hook: flush when due, else a no-op. Returns the
        (possibly reused) device state — rows are overwritten in place on
        the next cycle, so no re-zeroing transfer is needed."""
        if self.due(step):
            self.flush(tstate)
        return tstate

    def event(self, name: str, t: Optional[float] = None,
              dur_s: float = 0.0) -> None:
        """Append a host-side event line (launcher phases, checkpoint
        saves, ...) to the same stream."""
        self._writer.put([{"kind": "event", "name": name,
                           "t": time.time() if t is None else t,
                           "dur_s": dur_s}])

    @property
    def pulls(self) -> int:
        """Device->host transfers performed so far (test observability)."""
        return self._pulls

    def close(self, final_state=None) -> None:
        """Flush a trailing partial window (if given) and stop the
        writer after the queue drains."""
        if final_state is not None:
            host = _host_pull(final_state)
            self._pulls += 1
            tail = int(host["n"]) % self.every
            if tail:    # rows since the last flush boundary, no re-emits
                self._enqueue(host, count=tail)
        self._writer.flush(timeout=30)
        self._writer.close()


# --------------------------------------------------- facade-style wrapper
def instrument_train_step(step_fn, pipeline: TelemetryPipeline, cfg=None,
                          lr=None, beta1: float = 0.9, beta2: float = 0.95,
                          donate: bool = True, mesh=None, plan=None,
                          **step_kw):
    """Wrap a facade-contract step (`step_fn(params, opt_state, batch,
    ...) -> (loss, new_params, new_opt)`) with in-jit telemetry.

    Returns a jitted `fn(params, opt_state, batch, tstate) -> (loss,
    new_params, new_opt, tstate')` with params/opt/tstate donated (the
    same facade builder, so the donation policy cannot drift). Recorded
    scalars: loss; grad global-norm (recovered exactly from Adam-family
    second moments under "v" via the donation-preserving sum identity,
    falling back to the elementwise first-moment delta when only "m"
    exists, nan with neither — see grad_norm_from_moments); param
    global-norm; non-finite count over the updated params; lr.

    `lr` is FORWARDED to the wrapped step exactly like
    make_train_step's kwargs (and recorded); `beta1`/`beta2` are
    recorder-only — they must DESCRIBE the optimizer the step already
    uses, they do not configure it. `mesh`/`plan` pass through to the
    facade builder: the accumulator rides the planner-driven GSPMD
    step as a replicated donated leaf (docs/parallel_training.md), and
    the recorded scalars — global norms, the moment-sum identity — are
    full-tree reductions, so their values match the unsharded step's."""
    from ..models.facade import make_train_step, plan_step_cell
    if lr is not None:
        step_kw["lr"] = lr
    # pp>1 plans swap the family step for the full-manual pipelined one
    # (models/facade.plan_step_cell — the same seam the resilient guard
    # routes through, incl. the elastic rebuild hook's fresh-identity
    # subtlety); pp=1 keeps the historical partial
    inner, _outer, _plan_rebuild = plan_step_cell(
        step_fn, cfg=cfg, mesh=mesh, plan=plan, **step_kw)

    def instrumented(params, opt_state, batch, tstate):
        loss, new_params, new_opt = inner(params, opt_state, batch)
        scalars = {
            "loss": loss,
            "grad_norm": grad_norm_from_moments(
                opt_state, new_opt, beta1=beta1, beta2=beta2)
            if isinstance(opt_state, dict) else float("nan"),
            "param_norm": global_norm(new_params),
            "nonfinite": nonfinite_count(new_params),
        }
        if lr is not None and "lr" in pipeline.fields:
            scalars["lr"] = lr
        if "tokens" in pipeline.fields:
            # trained tokens this step, from the STATIC batch shape
            # ([B, S+1] next-token batches train B·S tokens) — a trace
            # constant, so the accumulator row costs nothing extra and
            # the loss math is untouched (bit-identical trajectories,
            # tests/test_train_observability.py)
            toks = batch["tokens"] if isinstance(batch, dict) else batch
            shape = getattr(toks, "shape", ())
            scalars["tokens"] = (
                float(shape[0] * (shape[1] - 1)) if len(shape) >= 2
                else float("nan"))
        scalars = {k: v for k, v in scalars.items()
                   if k in pipeline.fields}
        tstate = pipeline.device_record(tstate, **scalars)
        return loss, new_params, new_opt, tstate

    instrumented._plan_resolved = True
    instrumented._plan_rebuild = _plan_rebuild
    _outer["fn"] = instrumented
    return make_train_step(instrumented, donate=donate, extra_donate=(3,),
                           mesh=mesh, plan=plan)
