"""paddle_tpu.profiler — tracing/profiling subsystem.

Reference analog: python/paddle/profiler/profiler.py:340 (`Profiler` with
scheduler states), utils.py:37 (`RecordEvent`), profiler_statistic.py (stats
tables), timer.py (throughput/ips benchmark auto-attached to DataLoader);
C++ substrate paddle/fluid/platform/profiler/ (RecordEvent spans into a
host-event recorder + CUPTI tracer, chrome-trace export).

TPU-native design — two complementary recorders behind one API:
- Host spans: `RecordEvent` keeps a process-local span log (name, wall-time,
  nesting depth). On TPU the host side is dispatch/input-pipeline work; this
  is what `summary()` tabulates and what the ips timer reads. Zero deps.
- Device/XLA trace: when a trace dir is configured (`on_trace_ready=
  export_chrome_tracing(dir)` or `Profiler(trace_dir=...)`), start/stop wrap
  `jax.profiler.start_trace/stop_trace`, producing a TensorBoard-loadable
  XLA trace with per-op device timelines; `RecordEvent` doubles as a
  `jax.profiler.TraceAnnotation` so host spans appear on that timeline too.
  `export_chrome_trace(path)` additionally renders the host spans as a
  standalone chrome-trace JSON (Perfetto / chrome://tracing), written
  beside the device trace on Profiler.stop().

Runtime telemetry substrate (docs/observability.md): `monitor` is the
thread-safe counter/gauge registry (platform/monitor.h analog) the
instrumented hot paths publish into; `telemetry` is the batched
step-metrics JSONL pipeline; `flight_recorder` is the crash black box.
"""
from __future__ import annotations

import enum
import json
import threading
import time
from typing import Callable, Iterable, Optional

from .timer import benchmark  # noqa: F401  (reference: profiler/timer.py)
from . import monitor  # noqa: F401  (reference: platform/monitor.h)


class ProfilerState(enum.Enum):
    """Scheduler states (reference profiler.py:79)."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1          # accepted for API compat; mapped onto the device trace
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-indexed state machine: skip_first CLOSED steps, then cycles of
    [closed CLOSED, ready READY, record RECORD(last=RECORD_AND_RETURN)],
    `repeat` times (0 = forever). Reference: profiler.py make_scheduler."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_scheduler(_step: int) -> ProfilerState:
    return ProfilerState.RECORD


# ------------------------------------------------------------- span recorder
class _SpanLog:
    """Process-local completed-span log (the HostEventRecorder analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.spans = []          # (name, start, dur_s, depth, tid)
        self.enabled = True

    def depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    def push(self):
        self._tls.depth = self.depth() + 1

    def pop(self, name: str, start: float):
        d = self.depth() - 1
        self._tls.depth = d
        if self.enabled:
            with self._lock:
                self.spans.append((name, start, time.perf_counter() - start,
                                   d, threading.get_ident()))

    def clear(self):
        with self._lock:
            self.spans = []


_LOG = _SpanLog()


class RecordEvent:
    """Span context manager / decorator (reference utils.py:37). Records a
    host span and annotates the XLA trace when one is active."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start = None
        self._annot = None

    def begin(self):
        self._start = time.perf_counter()
        _LOG.push()
        try:
            import jax
            self._annot = jax.profiler.TraceAnnotation(self.name)
            self._annot.__enter__()
        except Exception:
            self._annot = None
        return self

    def end(self):
        if self._annot is not None:
            self._annot.__exit__(None, None, None)
            self._annot = None
        if self._start is not None:
            _LOG.pop(self.name, self._start)
            self._start = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapped


def export_chrome_trace(path: str, spans=None) -> str:
    """Write the completed host spans as a chrome-trace JSON file
    (reference ChromeTracingLogger, chrometracing_logger.h:31): complete
    "X" events with microsecond ts/dur keyed by pid/tid, loadable in
    Perfetto / chrome://tracing and by TensorBoard's trace viewer. The
    jax.profiler device trace (when a trace dir is active) is a separate
    TensorBoard artifact; this file covers the HOST side — dispatch,
    checkpoint IO, launcher phases — with zero device involvement.

    Atomic tmp+rename write; returns `path`."""
    import os
    spans = _LOG.spans if spans is None else spans
    pid = os.getpid()
    events = []
    for rec in list(spans):
        name, start, dur = rec[0], rec[1], rec[2]
        tid = rec[4] if len(rec) > 4 else 0
        events.append({
            "name": name, "ph": "X", "cat": "host",
            "ts": round(start * 1e6, 3), "dur": round(dur * 1e6, 3),
            "pid": pid, "tid": tid,
        })
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"producer": "paddle_tpu.profiler"}}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{pid}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory: configures the XLA trace dir (TensorBoard /
    chrome-trace loadable — reference ChromeTracingLogger analog)."""

    def handler(prof: "Profiler"):
        prof._trace_dir = dir_name
    handler._trace_dir = dir_name
    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Alias of export_chrome_tracing: the jax trace IS a protobuf dump."""
    return export_chrome_tracing(dir_name, worker_name)


class Profiler:
    """Reference-shaped profiler (profiler.py:340).

    with profiler.Profiler(scheduler=(2, 5)) as p:
        for batch in loader:
            train_step(...)
            p.step()
    print(p.summary())
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, timer_only: bool = False,
                 trace_dir: Optional[str] = None):
        if scheduler is None:
            self._schedule = _default_scheduler
        elif callable(scheduler):
            self._schedule = scheduler
        else:  # (start, end) step-range tuple, reference-accepted form
            lo, hi = scheduler
            self._schedule = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                            repeat=1)
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        self._trace_dir = trace_dir
        if on_trace_ready is not None:
            td = getattr(on_trace_ready, "_trace_dir", None)
            if td:
                self._trace_dir = td
        self._on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_tracing = False
        self._step_times = []
        self._last_step_t = None

    # -------------------------------------------------------------- control
    def start(self):
        benchmark().begin()
        self.current_state = self._schedule(self.step_num)
        self._sync_device_trace()
        self._last_step_t = time.perf_counter()
        return self

    def stop(self):
        if self._device_tracing:
            import jax
            jax.profiler.stop_trace()
            self._device_tracing = False
        benchmark().end()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        if self._trace_dir is not None and not self.timer_only:
            # host spans beside the jax.profiler device trace: one
            # Perfetto/chrome://tracing-loadable JSON per process
            import os
            try:
                export_chrome_trace(os.path.join(
                    self._trace_dir, f"host_trace.{os.getpid()}.json"))
            except OSError:
                pass
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        benchmark().step(num_samples)
        self.step_num += 1
        prev = self.current_state
        self.current_state = self._schedule(self.step_num)
        if prev != self.current_state:
            self._sync_device_trace()

    def _sync_device_trace(self):
        want = (self.current_state in (ProfilerState.RECORD,
                                       ProfilerState.RECORD_AND_RETURN)
                and self._trace_dir is not None and not self.timer_only)
        if want and not self._device_tracing:
            import jax
            jax.profiler.start_trace(self._trace_dir)
            self._device_tracing = True
        elif not want and self._device_tracing:
            import jax
            jax.profiler.stop_trace()
            self._device_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- reporting
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms") -> str:
        """Host-span stats table + step-time stats (the reference's
        profiler_statistic tables, host side)."""
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
        agg = {}
        for name, _start, dur, _depth, *_tid in _LOG.spans:
            c, tot, mx = agg.get(name, (0, 0.0, 0.0))
            agg[name] = (c + 1, tot + dur, max(mx, dur))
        lines = [f"{'name':<40} {'calls':>6} {'total':>10} {'avg':>10} "
                 f"{'max':>10}  ({time_unit})"]
        for name, (c, tot, mx) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40} {c:>6} {tot * unit:>10.3f} "
                         f"{tot / c * unit:>10.3f} {mx * unit:>10.3f}")
        if self._step_times:
            st = sorted(self._step_times)
            n = len(st)
            lines.append("")
            lines.append(
                f"steps: {n}  avg {sum(st) / n * unit:.3f}{time_unit}  "
                f"p50 {st[n // 2] * unit:.3f}{time_unit}  "
                f"min {st[0] * unit:.3f}{time_unit}  "
                f"max {st[-1] * unit:.3f}{time_unit}")
        return "\n".join(lines)

    @property
    def step_times(self):
        return list(self._step_times)


def get_profiler_spans():
    """Raw completed host spans [(name, start, dur_s, depth), ...]."""
    return list(_LOG.spans)


def clear_profiler_spans():
    _LOG.clear()


def load_profiler_result(filename: str):
    raise NotImplementedError(
        "XLA traces are TensorBoard artifacts; point TensorBoard at the "
        "trace dir passed to export_chrome_tracing instead.")


def cost_analysis(fn, *example_args, **jit_kwargs):
    """XLA's own static cost model for a jitted callable (reference
    analog: paddle/fluid/framework/ir/cost_model.py + the profiler's op
    FLOPs accounting). Returns a dict with flops, bytes accessed, and
    (when the backend reports it) optimal_seconds — computable without
    running the program, so it works even when no accelerator is
    reachable. Use it to sanity-check an MFU measurement: measured_time /
    (flops / peak_flops) is the achievable-vs-actual gap.

    Caveat: XLA counts a lax.scan/while body ONCE, not per iteration —
    for scan-stacked models (models.gpt) the reported flops are a lower
    bound; multiply the body's share by the trip count for truth."""
    import jax
    compiled = jax.jit(fn, **jit_kwargs).lower(*example_args).compile()
    raw = compiled.cost_analysis()
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else {}
    out = {"flops": float(raw.get("flops", 0.0)),
           "bytes_accessed": float(raw.get("bytes accessed", 0.0)),
           "optimal_seconds": float(raw.get("optimal_seconds", 0.0))}
    # mem_audit is THE home for compiled-memory reads; same historical
    # output keys (temp/argument/output_size_bytes) plus its extras
    from .mem_audit import compiled_memory_stats
    out.update(compiled_memory_stats(compiled))
    out["raw"] = dict(raw)
    return out


def __getattr__(name):
    # telemetry / flight_recorder pull in jax lazily; loading them only
    # on attribute access keeps `import paddle_tpu.profiler` backend-free
    # (serving_telemetry / tracing / slo are jax-free but ride the same
    # lazy seam so the profiler package stays import-light)
    if name in ("telemetry", "flight_recorder", "serving_telemetry",
                "tracing", "slo", "hlo_audit", "mem_audit"):
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class SortedKeys(enum.Enum):
    """reference profiler_statistic.py:49 — summary-table sort keys."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """reference profiler.py:46 — summary views."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
