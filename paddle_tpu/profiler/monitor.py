"""Exported runtime monitors: a thread-safe counter/gauge/histogram
registry.

Reference analog: paddle/fluid/platform/monitor.h:1 (the whole small
header: `StatValue<T>` slots + the `StatRegistry<int64_t>` /
`StatRegistry<float>` singletons PS and fleet components publish into
via `STAT_ADD(item, t)` / `STAT_INT(item)`; monitor.cc:1 instantiates
the registries — SURVEY §5 "Metrics/logging/observability"). Here one
registry holds three kinds — `Counter` (monotonic int, the STAT_INT
analog), `Gauge` (last-written float, the STAT_FLOAT analog), and
`Histogram` (bounded-reservoir latency distribution: the SLO-grade
upgrade over a last-write-wins gauge, cf. the reference profiler's
stat tables which report avg/max but lose percentiles) — and
`snapshot()` renders it for the telemetry JSONL stream and the flight
recorder. A histogram renders as a small dict
({"n","min","max","mean","p50","p95","p99"}), so snapshot values are
either numbers or dicts — tools/telemetry_report.py handles both.

Design constraints:
- import-light: framework/dispatch.py increments counters on the eager
  hot path, so this module must not import jax/numpy at module load.
- thread-safe: the resilient trainer's watchdog pull thread, the
  telemetry writer thread and user threads all publish concurrently
  (tests/test_telemetry.py hammers one counter from N threads).
- cheap: one small lock per stat; handles are resolved once and cached
  by the instrumented call sites, so the steady-state cost is
  lock+add.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Union


class Counter:
    """Monotonic integer stat (STAT_INT analog)."""

    kind = "counter"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def add(self, n: int = 1) -> int:
        with self._lock:
            self._value += int(n)
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written float stat (STAT_FLOAT analog)."""

    kind = "gauge"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> float:
        with self._lock:
            self._value = float(v)
            return self._value

    def add(self, v: float = 1.0) -> float:
        with self._lock:
            self._value += float(v)
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Latency/size distribution over a bounded reservoir.

    Reservoir sampling (Vitter's algorithm R) with a deterministic
    per-histogram PRNG: every observation is a candidate, the kept set
    is a uniform sample of everything ever observed, and memory is
    bounded at `reservoir` floats no matter how long the process
    serves. min/max/mean/count are tracked EXACTLY over all
    observations (they are not sampled); percentiles come from the
    reservoir. Determinism: the replacement stream is seeded from the
    stat name, so two runs observing the same sequence snapshot the
    same percentiles — test-assertable, like everything else in this
    registry."""

    kind = "histogram"
    DEFAULT_RESERVOIR = 2048
    __slots__ = ("name", "_lock", "_samples", "_cap", "_n", "_sum",
                 "_min", "_max", "_rng")

    def __init__(self, name: str, reservoir: int = DEFAULT_RESERVOIR):
        import random
        import zlib
        self.name = name
        self._lock = threading.Lock()
        self._cap = max(int(reservoir), 1)
        self._samples = []
        self._n = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._rng = random.Random(zlib.crc32(name.encode()))

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._n += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._samples) < self._cap:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self._n)
                if j < self._cap:
                    self._samples[j] = v

    def percentile(self, q: float):
        """Nearest-rank percentile over the reservoir (None when
        empty)."""
        with self._lock:
            ordered = sorted(self._samples)
        if not ordered:
            return None
        import math
        k = max(0, math.ceil(q / 100.0 * len(ordered)) - 1)
        return ordered[k]

    @property
    def value(self) -> dict:
        """The snapshot rendering: exact n/min/max/mean + reservoir
        percentiles, all rounded for stable JSONL output."""
        with self._lock:
            n, s = self._n, self._sum
            mn, mx = self._min, self._max
            ordered = sorted(self._samples)
        if not n:
            return {"n": 0}
        import math

        def pct(q):
            return ordered[max(0, math.ceil(q / 100.0 * len(ordered)) - 1)]
        return {"n": n, "min": round(mn, 3), "max": round(mx, 3),
                "mean": round(s / n, 3), "p50": round(pct(50), 3),
                "p95": round(pct(95), 3), "p99": round(pct(99), 3)}

    def reset(self) -> None:
        with self._lock:
            self._samples = []
            self._n = 0
            self._sum = 0.0
            self._min = self._max = None


Stat = Union[Counter, Gauge, Histogram]


class MonitorRegistry:
    """The process-wide stat table (StatRegistry::Instance analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Stat] = {}

    def _get(self, name: str, cls) -> Stat:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = cls(name)
                self._stats[name] = stat
            elif not isinstance(stat, cls):
                raise TypeError(
                    f"monitor stat {name!r} already registered as "
                    f"{stat.kind}, requested {cls.kind}")
            return stat

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def get(self, name: str):
        with self._lock:
            return self._stats.get(name)

    def snapshot(self) -> Dict[str, Union[int, float]]:
        """name -> value for every registered stat, name-sorted (stable
        JSONL/flight-dump layout)."""
        with self._lock:
            stats = list(self._stats.values())
        return {s.name: s.value for s in sorted(stats, key=lambda s: s.name)}

    def reset(self) -> None:
        """Zero every stat (tests). Handles stay valid — call sites cache
        them."""
        with self._lock:
            stats = list(self._stats.values())
        for s in stats:
            s.reset()

    def export_jsonl(self, path: str) -> None:
        """Append one monitor-snapshot line to a telemetry JSONL file
        (the schema tools/telemetry_report.py consumes)."""
        line = json.dumps({"kind": "monitor", "t": time.time(),
                           "pid": os.getpid(), "stats": self.snapshot()})
        with open(path, "a") as f:
            f.write(line + "\n")


_REGISTRY = MonitorRegistry()


def registry() -> MonitorRegistry:
    """The process-wide registry singleton."""
    return _REGISTRY


def counter(name: str) -> Counter:
    """Get-or-create the named counter (resolve once, cache the handle
    at hot call sites)."""
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge."""
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    """Get-or-create the named histogram (bounded reservoir; snapshot
    renders p50/p95/p99)."""
    return _REGISTRY.histogram(name)


def snapshot() -> Dict[str, Union[int, float]]:
    return _REGISTRY.snapshot()


# reference-shaped conveniences (monitor.h STAT_ADD / STAT_SETTER)
def stat_add(name: str, n: int = 1) -> int:
    return _REGISTRY.counter(name).add(n)


def stat_set(name: str, v: float) -> float:
    return _REGISTRY.gauge(name).set(v)
