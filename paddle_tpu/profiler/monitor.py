"""Exported runtime monitors: a thread-safe counter/gauge registry.

Reference analog: paddle/fluid/platform/monitor.h:1 (the whole small
header: `StatValue<T>` slots + the `StatRegistry<int64_t>` /
`StatRegistry<float>` singletons PS and fleet components publish into
via `STAT_ADD(item, t)` / `STAT_INT(item)`; monitor.cc:1 instantiates
the registries — SURVEY §5 "Metrics/logging/observability"). Here one
registry holds both kinds — `Counter` (monotonic int, the STAT_INT
analog) and `Gauge` (last-written float, the STAT_FLOAT analog) — and
`snapshot()` renders it for the telemetry JSONL stream and the flight
recorder.

Design constraints:
- import-light: framework/dispatch.py increments counters on the eager
  hot path, so this module must not import jax/numpy at module load.
- thread-safe: the resilient trainer's watchdog pull thread, the
  telemetry writer thread and user threads all publish concurrently
  (tests/test_telemetry.py hammers one counter from N threads).
- cheap: one small lock per stat; handles are resolved once and cached
  by the instrumented call sites, so the steady-state cost is
  lock+add.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Union


class Counter:
    """Monotonic integer stat (STAT_INT analog)."""

    kind = "counter"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def add(self, n: int = 1) -> int:
        with self._lock:
            self._value += int(n)
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written float stat (STAT_FLOAT analog)."""

    kind = "gauge"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> float:
        with self._lock:
            self._value = float(v)
            return self._value

    def add(self, v: float = 1.0) -> float:
        with self._lock:
            self._value += float(v)
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


Stat = Union[Counter, Gauge]


class MonitorRegistry:
    """The process-wide stat table (StatRegistry::Instance analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Stat] = {}

    def _get(self, name: str, cls) -> Stat:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = cls(name)
                self._stats[name] = stat
            elif not isinstance(stat, cls):
                raise TypeError(
                    f"monitor stat {name!r} already registered as "
                    f"{stat.kind}, requested {cls.kind}")
            return stat

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def get(self, name: str):
        with self._lock:
            return self._stats.get(name)

    def snapshot(self) -> Dict[str, Union[int, float]]:
        """name -> value for every registered stat, name-sorted (stable
        JSONL/flight-dump layout)."""
        with self._lock:
            stats = list(self._stats.values())
        return {s.name: s.value for s in sorted(stats, key=lambda s: s.name)}

    def reset(self) -> None:
        """Zero every stat (tests). Handles stay valid — call sites cache
        them."""
        with self._lock:
            stats = list(self._stats.values())
        for s in stats:
            s.reset()

    def export_jsonl(self, path: str) -> None:
        """Append one monitor-snapshot line to a telemetry JSONL file
        (the schema tools/telemetry_report.py consumes)."""
        line = json.dumps({"kind": "monitor", "t": time.time(),
                           "pid": os.getpid(), "stats": self.snapshot()})
        with open(path, "a") as f:
            f.write(line + "\n")


_REGISTRY = MonitorRegistry()


def registry() -> MonitorRegistry:
    """The process-wide registry singleton."""
    return _REGISTRY


def counter(name: str) -> Counter:
    """Get-or-create the named counter (resolve once, cache the handle
    at hot call sites)."""
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge."""
    return _REGISTRY.gauge(name)


def snapshot() -> Dict[str, Union[int, float]]:
    return _REGISTRY.snapshot()


# reference-shaped conveniences (monitor.h STAT_ADD / STAT_SETTER)
def stat_add(name: str, n: int = 1) -> int:
    return _REGISTRY.counter(name).add(n)


def stat_set(name: str, v: float) -> float:
    return _REGISTRY.gauge(name).set(v)
