"""Throughput / ips benchmark timer.

Reference analog: python/paddle/profiler/timer.py — a global Benchmark
object with begin/step/end hooks that the DataLoader attaches to, reporting
reader cost and ips (items per second) with warmup-aware summary stats.
"""
from __future__ import annotations

import time
from typing import Optional


class _Hint:
    def __init__(self):
        self.reader_cost = 0.0
        self.batch_cost = 0.0
        self.ips = 0.0


class Benchmark:
    """Step timer: call begin() once, step(num_samples) per iteration,
    end() to finish. `summary()` reports avg/p50 batch cost and ips,
    excluding the first `skip` steps (compile/warmup)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._begin_t: Optional[float] = None
        self._last_t: Optional[float] = None
        self._costs = []
        self._samples = []
        self._reader_t: Optional[float] = None
        self._reader_costs = []
        self.current_event = _Hint()

    def begin(self):
        self._begin_t = self._last_t = time.perf_counter()

    def before_reader(self):
        self._reader_t = time.perf_counter()

    def after_reader(self):
        if self._reader_t is not None:
            self._reader_costs.append(time.perf_counter() - self._reader_t)
            self._reader_t = None

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_t is not None:
            dt = now - self._last_t
            self._costs.append(dt)
            self._samples.append(num_samples or 0)
            self.current_event.batch_cost = dt
            if num_samples:
                self.current_event.ips = num_samples / dt
        self._last_t = now

    def end(self):
        self._last_t = None

    # ------------------------------------------------------------- reporting
    def step_info(self, unit: str = "samples") -> str:
        e = self.current_event
        msg = f"batch_cost: {e.batch_cost * 1e3:.2f} ms"
        if self._reader_costs:
            msg += f", reader_cost: {self._reader_costs[-1] * 1e3:.2f} ms"
        if e.ips:
            msg += f", ips: {e.ips:.1f} {unit}/s"
        return msg

    def summary(self, skip: int = 1) -> dict:
        costs = self._costs[skip:] if len(self._costs) > skip else self._costs
        samples = (self._samples[skip:] if len(self._samples) > skip
                   else self._samples)
        if not costs:
            return {"steps": 0}
        total = sum(costs)
        n = len(costs)
        ordered = sorted(costs)
        out = {
            "steps": n,
            "samples": sum(samples),
            "avg_batch_cost_s": total / n,
            "p50_batch_cost_s": ordered[n // 2],
            # nearest-rank p95: the tail a p50/avg pair hides (one slow
            # reader stall or tunnel flap per 20 steps shows up here)
            "p95_batch_cost_s": ordered[max(0, -(-95 * n // 100) - 1)],
        }
        tot_samples = sum(samples)
        if tot_samples:
            out["ips"] = tot_samples / total
        if self._reader_costs:
            out["avg_reader_cost_s"] = (sum(self._reader_costs)
                                        / len(self._reader_costs))
        return out


_BENCHMARK = Benchmark()


def benchmark() -> Benchmark:
    """The global Benchmark singleton (reference timer.py benchmark())."""
    return _BENCHMARK
