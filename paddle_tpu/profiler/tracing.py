"""Request-scoped distributed tracing for the serving fleet.

Reference analog: python/paddle/profiler/utils.py:37 (`RecordEvent`
host spans) generalized from thread-scoped nesting to REQUEST-scoped
parenting: a serving request's lifecycle crosses the router thread,
N engine worker threads, and (on replica death) engine instances — the
thread-local depth stack of profiler._SpanLog cannot follow it, so
spans here carry explicit (trace_id, span_id, parent_id) like any
OpenTelemetry-shaped tracer.

Model:
- `Tracer` — process-global span log (thread-safe, bounded). One per
  process; engines and routers share it so a request's spans land in
  one timeline no matter which component emitted them.
- `RequestTrace` — the context minted at `submit()` and carried on the
  Request object through router admission → dispatch → prefill chunks
  → decode ticks → the terminal `_finish`. Spans open/close by id
  (no thread-local state), instants record point events (per-tick
  token emissions, dispatch decisions), and `finish()` emits the ONE
  terminal span — it is called from the `_finish` seams (engine and
  router both) and is once-only by construction, so a routed request
  whose inner terminal translates to the outer one still exports
  exactly one terminal event.
- Replica death: `sever()` closes every open span in the tree (tagged
  `severed`) WITHOUT finishing the trace, and `link_replay()` opens a
  fresh attempt span parented at the root and linked to the severed
  subtree — the replayed request's prefill/decode spans parent into
  the attempt, so the export shows attempt 0 cut short, the death
  event, and attempt 1 carrying the stream to its terminal span.

Export: `export_chrome_trace(path)` writes Perfetto /
chrome://tracing-loadable JSON — each trace (request) gets its own tid
lane with a thread_name metadata record, spans are complete "X"
events whose args carry span/parent ids and attrs, instants are "i"
events. The PR-3 host-span log (profiler.RecordEvent) is a separate,
complementary timeline (per-thread engine internals); this one is
per-request.

Overhead: tracing is OFF by default (`ServingEngine(tracing=True)` /
`create_router(tracing=True)` opt in). Every emit is one tuple append
under a lock; the bounded deque caps memory for long-lived servers.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Tracer", "RequestTrace", "Span", "tracer", "clear"]


class Span:
    """One completed or open span. `dur` is None while open."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "dur", "attrs", "kind")

    def __init__(self, trace_id, span_id, parent_id, name, t0,
                 kind="span", attrs=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.dur = None
        self.kind = kind                   # span | instant | terminal
        self.attrs = attrs or {}

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "t0": self.t0, "dur": self.dur, "kind": self.kind,
                "attrs": dict(self.attrs)}


class Tracer:
    """Process-global request-span log."""

    def __init__(self, max_spans: int = 65536):
        import collections
        self._lock = threading.Lock()
        self._spans: "collections.deque[Span]" = \
            collections.deque(maxlen=max_spans)
        self._open: Dict[int, Span] = {}     # span_id -> open span
        self._next_trace = 0
        self._next_span = 0

    # ------------------------------------------------------------ minting
    def trace(self, name: str, **attrs) -> "RequestTrace":
        """Mint a new trace: opens its root span and returns the
        context to thread through the request's lifecycle."""
        with self._lock:
            tid = self._next_trace
            self._next_trace += 1
        t = RequestTrace(self, tid, name)
        t.root = t.begin(name, parent=None, **attrs)
        return t

    def _begin(self, trace_id, name, parent_id, kind="span",
               **attrs) -> int:
        sp = Span(trace_id, 0, parent_id, name, time.perf_counter(),
                  kind=kind, attrs=attrs)
        with self._lock:
            sp.span_id = self._next_span
            self._next_span += 1
            if kind == "span":
                self._open[sp.span_id] = sp
            else:
                sp.dur = 0.0
                self._spans.append(sp)
        return sp.span_id

    def _end(self, span_id, **attrs) -> None:
        with self._lock:
            sp = self._open.pop(span_id, None)
            if sp is None:
                return                       # already closed (idempotent)
            sp.dur = time.perf_counter() - sp.t0
            if attrs:
                sp.attrs.update(attrs)
            self._spans.append(sp)

    def _open_of(self, trace_id) -> List[int]:
        with self._lock:
            return [sid for sid, sp in self._open.items()
                    if sp.trace_id == trace_id]

    # ------------------------------------------------------------- access
    def spans(self, trace_id: Optional[int] = None) -> List[Span]:
        """Completed spans (open ones are not included until ended)."""
        with self._lock:
            spans = list(self._spans)
        if trace_id is None:
            return spans
        return [s for s in spans if s.trace_id == trace_id]

    def trace_ids(self) -> List[int]:
        return sorted({s.trace_id for s in self.spans()})

    def terminal_spans(self, trace_id: Optional[int] = None) -> List[Span]:
        return [s for s in self.spans(trace_id) if s.kind == "terminal"]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._open.clear()

    # ------------------------------------------------------------- export
    def export_chrome_trace(self, path: str) -> str:
        """Chrome-trace JSON: one tid lane per trace (request), "X"
        events for spans (args carry span/parent ids + attrs), "i"
        instants for point events. Atomic tmp+rename like
        profiler.export_chrome_trace."""
        pid = os.getpid()
        events = []
        lanes = {}
        for sp in self.spans():
            lane = lanes.setdefault(sp.trace_id, len(lanes))
            args = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                    "trace_id": sp.trace_id}
            args.update({k: v for k, v in sp.attrs.items()
                         if isinstance(v, (int, float, str, bool,
                                           type(None)))})
            ev = {"name": sp.name, "pid": pid, "tid": lane,
                  "ts": round(sp.t0 * 1e6, 3), "cat": "request",
                  "args": args}
            if sp.kind == "span":
                ev["ph"] = "X"
                ev["dur"] = round((sp.dur or 0.0) * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
                if sp.kind == "terminal":
                    ev["cat"] = "terminal"
            events.append(ev)
        for trace_id, lane in lanes.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": lane,
                           "args": {"name": f"request-{trace_id}"}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"producer": "paddle_tpu.profiler.tracing"}}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{pid}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


class RequestTrace:
    """The per-request context: explicit-parent span emission plus the
    once-only terminal transition. Thread-safe through the tracer's
    lock; the `finish` flag has its own tiny lock so the engine's and
    the router's `_finish` seams can race benignly."""

    __slots__ = ("_tracer", "trace_id", "name", "root", "_finished",
                 "_flock", "attempt")

    def __init__(self, tracer: Tracer, trace_id: int, name: str):
        self._tracer = tracer
        self.trace_id = trace_id
        self.name = name
        self.root: Optional[int] = None
        self.attempt = 0                 # bumps on replica-death replay
        self._finished = False
        self._flock = threading.Lock()

    @property
    def finished(self) -> bool:
        return self._finished

    # -------------------------------------------------------------- spans
    def begin(self, name: str, parent: Optional[int] = "root",
              **attrs) -> int:
        """Open a span; `parent` defaults to the root span."""
        pid = self.root if parent == "root" else parent
        return self._tracer._begin(self.trace_id, name, pid, **attrs)

    def end(self, span_id: Optional[int], **attrs) -> None:
        if span_id is not None:
            self._tracer._end(span_id, **attrs)

    def instant(self, name: str, parent: Optional[int] = "root",
                **attrs) -> int:
        pid = self.root if parent == "root" else parent
        return self._tracer._begin(self.trace_id, name, pid,
                                   kind="instant", **attrs)

    # ---------------------------------------------------------- lifecycle
    def finish(self, reason: str, **attrs) -> bool:
        """THE terminal transition: close every open span of this trace
        (root included) and emit the one terminal event. Once-only —
        the engine's `_finish` and the router's `_finish` both call
        this; whichever lands first wins and the other is a no-op, so
        every request exports EXACTLY one terminal span. Returns True
        when this call emitted it."""
        with self._flock:
            if self._finished:
                return False
            self._finished = True
        self._tracer._begin(self.trace_id, "finish", self.root,
                            kind="terminal", reason=reason, **attrs)
        for sid in self._tracer._open_of(self.trace_id):
            self._tracer._end(sid, finish_reason=reason)
        return True

    def sever(self, reason: str, **attrs) -> None:
        """Replica death: close the trace's open span subtree (tagged
        severed) WITHOUT finishing — the request will replay. Records
        the death as an instant so the export shows the cut."""
        self.instant("severed", reason=reason, attempt=self.attempt,
                     **attrs)
        for sid in self._tracer._open_of(self.trace_id):
            self._tracer._end(sid, severed=True, severed_reason=reason)

    def link_replay(self, **attrs) -> int:
        """Record the replay link: bumps the attempt index and emits a
        "replay" instant parented at the root. The replaying engine
        does not need to know it is a replay — its spans parent at the
        root as usual, and the attempt counter in this instant is the
        link between the severed subtree and the fresh one."""
        self.attempt += 1
        return self.instant("replay", attempt=self.attempt, **attrs)


_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def tracer() -> Tracer:
    """The process-wide tracer singleton (engines and routers share
    it — a request's spans land in one timeline)."""
    global _TRACER
    with _TRACER_LOCK:
        if _TRACER is None:
            _TRACER = Tracer()
        return _TRACER


def clear() -> None:
    """Drop every recorded span (tests / chaos scenarios)."""
    tracer().clear()
