"""Compiled-memory audit: what the compiled step REALLY allocates.

Reference analog: the device_memory_stat peak trackers
(paddle/fluid/memory/stats.h STAT_GPU registries + the
memory_optimize pass's estimated-vs-allocated accounting). TPU-native
collapse: XLA's ahead-of-time memory accounting IS the allocator
ledger — `compiled.memory_analysis()` reports per-device temp /
argument / output / alias / generated-code bytes for the exact
executable that will run, so the audit lowers the ACTUAL pinned train
step (the hlo_audit seam: `jax.jit(...).lower(...).compile()` over
abstract avals, no params materialized) or the serving decode tick
and reads the compiler's numbers instead of sampling an allocator.

The diff against `cost_model.train_memory_ledger` /
`serving_memory_ledger` is the product: the ledger is the planner's
HBM gate (parallel/planner._estimate consumes it verbatim), so a gap
beyond tolerance means the gate is mis-pricing plans — surfaced as a
NAMED finding (`hbm_underestimate` / `hbm_overestimate`, naming the
plan and the ledger's largest component as the prime suspect) instead
of a mystery OOM three PRs later. tools/mem_attrib.py renders the
join; tools/mem_gate.py pins the compiled peak per canonical plan so
regressions fail `chaos_drill --gate` at commit time.

This module is also the ONE home for reading memory_analysis():
`profiler.cost_analysis` delegates to `compiled_memory_stats` (same
output keys as its historical inline getattr), and
`ServingEngine.compiled_memory_stats()` routes here too.
"""
from __future__ import annotations

import time
from typing import Optional

from . import monitor

# CompiledMemoryStats attribute -> output key (the first three are the
# historical profiler.cost_analysis keys — preserved verbatim)
_STAT_KEYS = (
    ("temp_size_in_bytes", "temp_size_bytes"),
    ("argument_size_in_bytes", "argument_size_bytes"),
    ("output_size_in_bytes", "output_size_bytes"),
    ("alias_size_in_bytes", "alias_size_bytes"),
    ("generated_code_size_in_bytes", "generated_code_size_bytes"),
)


def compiled_memory_stats(compiled) -> dict:
    """Read `compiled.memory_analysis()` into a plain dict (empty when
    the backend doesn't report). `peak_bytes` is the per-device HBM
    envelope: arguments + outputs + temporaries + generated code,
    minus the aliased (donated) bytes that arguments and outputs
    double-count."""
    mem = getattr(compiled, "memory_analysis", None)
    if not callable(mem):
        return {}
    try:
        m = mem()
    except Exception:                              # noqa: BLE001
        return {}
    out = {}
    for attr, key in _STAT_KEYS:
        v = getattr(m, attr, None)
        if v is not None:
            out[key] = int(v)
    if out:
        out["peak_bytes"] = max(
            out.get("temp_size_bytes", 0)
            + out.get("argument_size_bytes", 0)
            + out.get("output_size_bytes", 0)
            + out.get("generated_code_size_bytes", 0)
            - out.get("alias_size_bytes", 0), 0)
    return out


def diff_vs_ledger(compiled_stats: dict, ledger: dict, plan_name: str,
                   tolerance: float = 0.5) -> list:
    """Audit findings: the compiled peak vs the ledger total, named by
    failure mode when the relative gap exceeds `tolerance`. The
    ledger's largest component is named as the prime suspect — the
    accounting is per-component on the estimate side only, so the
    finding points at where the bytes were (or weren't) budgeted."""
    peak = compiled_stats.get("peak_bytes")
    total = ledger.get("total") or 0.0
    if peak is None or total <= 0:
        return []
    gap = (peak - total) / total
    if abs(gap) <= tolerance:
        return []
    comps = ledger.get("components") or {}
    largest = max(comps, key=comps.get) if comps else "?"
    kind = "hbm_underestimate" if gap > 0 else "hbm_overestimate"
    return [{
        "kind": kind, "plan": plan_name,
        "compiled_peak_bytes": int(peak), "ledger_bytes": int(total),
        "gap_fraction": round(gap, 4),
        "largest_component": largest,
        "detail": (f"plan {plan_name}: compiled peak "
                   f"{peak / 1e6:.1f} MB vs ledger "
                   f"{total / 1e6:.1f} MB ({gap:+.0%}, tolerance "
                   f"{tolerance:.0%}); largest ledger component: "
                   f"{largest} ({comps.get(largest, 0) / 1e6:.1f} MB)"),
    }]


def audit_train_memory(cfg, plan, global_batch: int, seq: int = 0,
                       family: str = "gpt", lr: float = 1e-3,
                       tolerance: float = 0.5) -> dict:
    """Lower + compile the ACTUAL planner-driven GSPMD train step for
    (cfg, plan) over abstract avals (the hlo_audit.audit_train_step
    lowering, byte-identical recipe) and diff XLA's compiled memory
    accounting against the train_memory_ledger the planner gated the
    plan with. Returns {"plan", "n_devices", "compile_ms", "compiled",
    "ledger", "gap_fraction", "findings"} and publishes
    `train.mem.audit_ms` / `train.mem.audits` /
    `train.mem.audit_findings` monitor stats."""
    import jax
    import jax.numpy as jnp
    from ..cost_model import train_memory_ledger
    from ..models import facade, gpt as gpt_mod, llama as llama_mod
    fam = {"gpt": gpt_mod, "llama": llama_mod}[family]
    seq = int(seq or cfg.max_seq_len)
    init = {"gpt": "init_gpt_params",
            "llama": "init_llama_params"}[family]
    params = jax.eval_shape(
        lambda k: getattr(fam, init)(cfg, k), jax.random.PRNGKey(0))
    opt = jax.eval_shape(gpt_mod.init_opt_state, params)
    toks = jax.ShapeDtypeStruct((int(global_batch), seq + 1), jnp.int32)
    mesh = plan.build_mesh()
    step = facade.make_train_step(fam.train_step, cfg=cfg, lr=lr,
                                  mesh=mesh, plan=plan)
    args = (params, opt, toks)
    step._build(args)
    t0 = time.perf_counter()
    compiled = step._jit.lower(*args).compile()
    compile_ms = (time.perf_counter() - t0) * 1e3
    stats = compiled_memory_stats(compiled)
    ledger = train_memory_ledger(cfg, plan, global_batch, seq=seq)
    name = getattr(plan, "name", str(plan))
    findings = diff_vs_ledger(stats, ledger, name, tolerance)
    peak, total = stats.get("peak_bytes"), ledger["total"]
    monitor.gauge("train.mem.audit_ms").set(round(compile_ms, 3))
    monitor.counter("train.mem.audits").add()
    monitor.gauge("train.mem.audit_findings").set(len(findings))
    if peak is not None:
        monitor.gauge("train.mem.compiled_peak_bytes").set(int(peak))
    return {
        "plan": name,
        "n_devices": int(mesh.devices.size),
        "compile_ms": round(compile_ms, 1),
        "compiled": stats,
        "ledger": ledger,
        "gap_fraction": (round((peak - total) / total, 4)
                         if peak is not None and total else None),
        "findings": findings,
    }


def audit_serving_memory(engine, tolerance: float = 0.5,
                         sampling: bool = False) -> dict:
    """The serving sibling: lower the engine's ACTUAL decode tick over
    the avals of its live state (ServingEngine.compiled_memory_stats —
    no tick dispatched, no host pull) and diff against its
    serving_memory_ledger. Publishes `serving.mem.audits` /
    `serving.mem.audit_findings`."""
    stats = engine.compiled_memory_stats(sampling=sampling)
    ledger = engine.memory_ledger()
    name = "{}_{}".format(
        ledger["config"]["layout"],
        "int8" if ledger["config"]["quant"] == "int8" else "fp")
    findings = diff_vs_ledger(stats, ledger, name, tolerance)
    peak, total = stats.get("peak_bytes"), ledger["total"]
    monitor.counter("serving.mem.audits").add()
    monitor.gauge("serving.mem.audit_findings").set(len(findings))
    if peak is not None:
        monitor.gauge("serving.mem.compiled_peak_bytes").set(int(peak))
    return {
        "plan": name,
        "compiled": stats,
        "ledger": ledger,
        "gap_fraction": (round((peak - total) / total, 4)
                         if peak is not None and total else None),
        "findings": findings,
    }


def live_array_census(limit: int = 32) -> dict:
    """Live device arrays summarized by (shape, dtype, sharding spec):
    {"rows": {key: {"count", "bytes"}}, "total_bytes"} — byte-sorted,
    truncated to the `limit` largest groups. The oom_forensics page
    that names the tenants. Host-side metadata reads only (shape /
    dtype / sharding / nbytes); never touches array contents."""
    import jax
    import numpy as np
    rows: dict = {}
    total = 0
    for a in jax.live_arrays():
        try:
            spec = getattr(getattr(a, "sharding", None), "spec", None)
            key = f"{tuple(a.shape)}/{np.dtype(a.dtype).name}/{spec}"
            nbytes = int(a.nbytes)
        except Exception:                          # noqa: BLE001
            continue
        row = rows.setdefault(key, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += nbytes
        total += nbytes
    rows = dict(sorted(rows.items(),
                       key=lambda kv: -kv[1]["bytes"])[:int(limit)])
    return {"rows": rows, "total_bytes": total}


def host_rss_bytes() -> Optional[int]:
    """Resident-set bytes of this process (the CPU-rung stand-in for
    hbm.bytes_in_use when the backend reports no device stats)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:                              # noqa: BLE001
        try:
            import resource
            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:                          # noqa: BLE001
            return None


def publish_hbm_gauges() -> None:
    """`hbm.bytes_in_use` / `hbm.peak_bytes` gauges from
    device.memory_stats() — the max across local devices (the
    OOM-relevant envelope) plus per-device `.d<i>` detail when more
    than one device reports. Host-RSS fallback when the backend
    reports nothing (CPU). Pure host-side PJRT reads: zero extra
    device pulls, so telemetry-on streams stay bit-identical.
    Callers: TelemetryPipeline flushes and ServingTelemetry pushes —
    the existing cadences, no new timers."""
    import jax
    from ..device import memory_stats
    rows = []
    try:
        devices = jax.local_devices()
    except Exception:                              # noqa: BLE001
        devices = []
    for i, d in enumerate(devices):
        st = memory_stats(d)
        if st:
            rows.append((i, int(st.get("bytes_in_use", 0)),
                         int(st.get("peak_bytes_in_use", 0))))
    if rows:
        monitor.gauge("hbm.bytes_in_use").set(max(r[1] for r in rows))
        monitor.gauge("hbm.peak_bytes").set(max(r[2] for r in rows))
        if len(rows) > 1:
            for i, used, peak in rows:
                monitor.gauge(f"hbm.bytes_in_use.d{i}").set(used)
                monitor.gauge(f"hbm.peak_bytes.d{i}").set(peak)
        return
    rss = host_rss_bytes()
    if rss is None:
        return
    g = monitor.gauge("hbm.bytes_in_use")
    g.set(rss)
    peak_g = monitor.gauge("hbm.peak_bytes")
    peak_g.set(max(rss, int(peak_g.value)))
