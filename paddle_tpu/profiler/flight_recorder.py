"""Crash flight recorder: the black box for the fault-tolerant runtime.

Reference analog: none in-tree — the reference's post-mortem story is
log scraping (SURVEY §5). PR 2's runtime (skip-step, rollback, watchdog,
elastic restart) recovers from faults but kept no record of what the
last steps looked like; this module is that record: a bounded ring of
the last N host-side step records plus the monitor snapshot, the run
config, and the most recent host spans, dumped as ONE JSON file via the
checkpoint module's tmp+rename idiom.

Dump triggers (wired in parallel/resilience.py, distributed/launch/
main.py and hapi/callbacks.py):
- watchdog fire (StepHungError / elastic exit-101),
- rollback,
- process exit with a failure (atexit + sys.excepthook),
- and a low-cost per-step autodump (no fsync: an `os._exit` hard kill
  skips atexit, but page-cache contents survive process death — only a
  machine crash can lose the last autodump, and that scenario is the
  checkpoint manifest's job, not the flight recorder's).

The dump directory comes from $PADDLE_TPU_FLIGHT_DIR (the launcher
exports it per worker); with no directory configured every call is a
cheap no-op, so production code paths stay instrumented
unconditionally.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

from . import monitor

ENV_DIR = "PADDLE_TPU_FLIGHT_DIR"
ENV_N = "PADDLE_TPU_FLIGHT_N"            # ring size (default 64)
ENV_AUTODUMP = "PADDLE_TPU_FLIGHT_AUTODUMP"  # steps between autodumps (1)


class FlightRecorder:
    """Bounded ring of step records + context, atomically dumpable."""

    def __init__(self, dir: Optional[str] = None, n: Optional[int] = None,
                 autodump_every: Optional[int] = None):
        self._lock = threading.Lock()
        self.dir = dir if dir is not None else os.environ.get(ENV_DIR)
        n = n if n is not None else int(os.environ.get(ENV_N, "64"))
        self._ring: deque = deque(maxlen=max(int(n), 1))
        self.autodump_every = (autodump_every if autodump_every is not None
                               else int(os.environ.get(ENV_AUTODUMP, "1")))
        self.config: dict = {}
        self._notes = 0
        self._hooks_installed = False

    # ------------------------------------------------------------ recording
    def set_dir(self, dir: Optional[str]) -> None:
        self.dir = dir

    def configure(self, **run_config) -> None:
        """Merge run-level context (model/resilience config, world size,
        argv...) into the dump header."""
        with self._lock:
            self.config.update(run_config)

    def note(self, **record) -> None:
        """Append one step record (host-side scalars only — this runs
        after the step's own host pull, it must never force one)."""
        record.setdefault("t", time.time())
        with self._lock:
            self._ring.append(record)
            self._notes += 1
            due = (self.dir and self.autodump_every > 0
                   and self._notes % self.autodump_every == 0)
        if due:
            self.dump("periodic", fsync=False)

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._notes = 0
            self.config = {}

    # -------------------------------------------------------------- dumping
    def _default_path(self, reason: str) -> Optional[str]:
        if not self.dir:
            return None
        # rolling reasons share one file; eventful triggers (rollback,
        # watchdog, exception...) get a reason-tagged file so later
        # periodic autodumps cannot overwrite the evidence
        if reason in ("periodic", "exit"):
            return os.path.join(self.dir, f"flight-{os.getpid()}.json")
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)
        return os.path.join(self.dir, f"flight-{os.getpid()}-{safe}.json")

    def dump(self, reason: str, path: Optional[str] = None,
             fsync: bool = True) -> Optional[str]:
        """Write the black box as one JSON file via tmp+rename (the
        checkpoint crash-safety idiom: readers never see a torn file).
        Returns the path, or None when no directory is configured."""
        path = path or self._default_path(reason)
        if path is None:
            return None
        with self._lock:
            doc = {
                "kind": "flight_recorder",
                "reason": reason,
                "t": time.time(),
                "pid": os.getpid(),
                "config": dict(self.config),
                "steps": list(self._ring),
                "monitor": monitor.snapshot(),
            }
        try:
            from .. import profiler as _prof
            doc["spans"] = [
                {"name": n, "start": s, "dur_s": d, "depth": depth}
                for (n, s, d, depth, *_t) in _prof.get_profiler_spans()[-64:]]
        except Exception:
            pass
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(doc))
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            print(f"[flight] dump failed: {e}", file=sys.stderr, flush=True)
            return None
        return path

    # ----------------------------------------------------------- exit hooks
    def install_exit_hooks(self) -> None:
        """Dump on process exit (atexit) and on uncaught exceptions.
        Idempotent; a no-op until a dump directory is configured —
        ResilientTrainer calls this unconditionally."""
        if self._hooks_installed:
            return
        self._hooks_installed = True
        import atexit

        def _on_exit():
            if self._ring and self.dir:
                self.dump("exit")
        atexit.register(_on_exit)

        prev = sys.excepthook

        def _on_exc(exc_type, exc, tb):
            try:
                self.configure(last_exception=f"{exc_type.__name__}: {exc}")
                if self.dir:
                    self.dump("exception")
            finally:
                prev(exc_type, exc, tb)
        sys.excepthook = _on_exc


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-wide recorder (created lazily so $PADDLE_TPU_FLIGHT_DIR
    set by the launcher's boot shim is read after it is exported)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def note(**record) -> None:
    recorder().note(**record)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    return recorder().dump(reason, path)


def load_dump(path: str) -> dict:
    """Parse a flight dump back (chaos-drill assertions / post-mortems)."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != "flight_recorder":
        raise ValueError(f"{path!r} is not a flight-recorder dump")
    return doc
