"""In-tick serving telemetry: device-computed per-tick fields riding
the token pull.

Reference analog: the profiler/monitor export loops that stream serving
stats (paddle/fluid/platform/monitor.h:1 registries fed by the fleet
serving deployments; python/paddle/profiler/profiler.py:340 stats
pipeline) — the serving sibling of profiler/telemetry.py's training
accumulator.

TPU-native design: the training pipeline batches K steps into a donated
device accumulator because the train loop makes NO per-step host pull.
The serving tick is different — it already pays EXACTLY ONE pull per
tick (the sampled-token array, inference/serving.py `_pull`), so the
cheapest possible telemetry is to PIGGYBACK on that pull: the jitted
tick computes a small int32 field vector (tokens emitted, active
slots, poisoned rows, cache tokens attended, spec proposed/accepted)
and returns it NEXT TO the token array; the host fetches both in the
same `_pull` call (one `jax.device_get` of the pair). Zero extra
pulls, zero extra traces — the field math is a handful of masked
reductions baked into the existing tick executable, and the engine's
one-pull/trace-ceiling tests run with telemetry ON
(tests/test_serving_observability.py asserts both).

Host-side, each tick's device row joins the scheduler's own knowledge
(queue depth, mid-prefill slot count, pages in use) plus the tick's
wall duration into one `serving_tick` record, kept in a bounded ring
and optionally streamed to a JSONL file through the same background
writer the training pipeline uses (profiler/telemetry.TelemetryWriter
— flush boundaries never block the tick on json/disk). Prefill device
calls get their own `serving_prefill` records. tools/telemetry_report.py
summarizes the stream; tools/serving_attrib.py joins per-tick ms with
the cost-model ledger into the achieved-vs-roofline report — the
`attended` field (kernels/decode_attention.attended_tokens) is what
prices the attention/KV-gather phases against what the tick actually
read.

JSONL schema (appended to the telemetry stream, same file as monitor
snapshots / serving_slo records):
  {"kind": "serving_run",     "t", "pid", "fields", ...meta}
  {"kind": "serving_tick",    "tick", "t", "dur_ms", <field>: int, ...,
                              "queue_depth", "prefilling", "pages_in_use"}
  {"kind": "serving_prefill", "tick", "t", "dur_ms", "chunk_len",
                              "bucket", "final", "slot"}

Kill switch: PADDLE_TPU_SERVING_TELEMETRY — off values disable the
in-tick fields for new engines (the tick then returns exactly the
PR-4..9 shape); default ON (the fields are a few reductions riding a
pull that happens anyway; measured overhead is recorded in BASELINE.md
"Serving observability").
"""
from __future__ import annotations

import collections
import os
import time
from typing import Optional

ENV_SERVING_TELEMETRY = "PADDLE_TPU_SERVING_TELEMETRY"

# device-computed per-tick fields, in row order (int32):
#   tokens        tokens this tick emitted (poisoned rows excluded)
#   active        slots the tick advanced
#   poisoned      rows the in-jit quarantine flagged this tick
#   attended      cache tokens the tick's attention admitted
#                 (kernels/decode_attention.attended_tokens — the
#                 roofline-attribution tap)
#   spec_proposed drafts proposed this tick (greedy slots x gamma)
#   spec_accepted drafts the verify pass kept
TICK_FIELDS = ("tokens", "active", "poisoned", "attended",
               "spec_proposed", "spec_accepted")

_OFF_VALUES = frozenset({"0", "off", "false", "no"})
_ON_VALUES = frozenset({"1", "on", "true", "yes"})


def resolve_serving_telemetry(knob: str = "auto") -> bool:
    """Engine-build resolution of the telemetry knob ('auto' | 'on' |
    'off') against the env kill switch. Unlike the spec/quant
    selectors the default is ON — the fields ride a pull that happens
    anyway — but the env override is a KILL SWITCH, so it only wins in
    the OFF direction: an env off value disables even knob='on', while
    an env on value never overrides an explicit knob='off' (an
    exported leftover must not silently re-enable the instrumented
    tick — e.g. bench_serving's A/B baseline pins telemetry='off' and
    must stay off). Unrecognized env values warn and defer to the
    knob."""
    env = os.environ.get(ENV_SERVING_TELEMETRY, "").strip().lower()
    if env and env in _OFF_VALUES:
        return False
    if env and env not in _ON_VALUES:
        import sys
        print(f"[serving_telemetry] {ENV_SERVING_TELEMETRY}={env!r} is "
              f"not one of {sorted(_ON_VALUES)} / {sorted(_OFF_VALUES)}; "
              "ignoring", file=sys.stderr, flush=True)
    if knob == "off":
        return False
    if knob in ("auto", "on"):
        return True
    raise ValueError(f"telemetry {knob!r} (auto|on|off)")


def pack_tick_fields(**fields):
    """In-jit: stack the named scalars into the TICK_FIELDS int32 row
    the tick returns beside the token array (missing fields record 0;
    unknown names raise at trace time)."""
    import jax.numpy as jnp
    unknown = set(fields) - set(TICK_FIELDS)
    if unknown:
        raise ValueError(f"unknown tick fields {sorted(unknown)}; "
                         f"declared fields are {TICK_FIELDS}")
    return jnp.stack([jnp.asarray(fields.get(f, 0), jnp.int32)
                      for f in TICK_FIELDS])


class ServingTelemetry:
    """Host half of the in-tick pipeline: a bounded in-memory ring of
    per-tick records (always on — tools and tests read it through
    `ServingEngine.tick_records()`) plus an optional JSONL stream
    drained by a background writer thread."""

    def __init__(self, path: Optional[str] = None, every: int = 32,
                 ring: int = 4096, meta: Optional[dict] = None,
                 on_flush=None):
        if every < 1:
            raise ValueError("every must be >= 1")
        self.path = path
        self.every = int(every)
        # flush-cadence tap: called (no args, exceptions swallowed)
        # every time a pending batch drains — the engine hangs its
        # host-bookkeeping gauges here (host-tier KV bytes, ticks per
        # pull) so they update on the SAME cadence as the HBM gauges
        # with zero extra device pulls
        self.on_flush = on_flush
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(ring), 1))
        self._pending: list = []
        self._writer = None
        if path:
            from .telemetry import TelemetryWriter
            self._writer = TelemetryWriter(path)
            header = {"kind": "serving_run", "t": time.time(),
                      "pid": os.getpid(), "fields": list(TICK_FIELDS)}
            if meta:
                header.update(meta)
            self._writer.put([header])

    # ------------------------------------------------------------ records
    def record_tick(self, tick: int, dev_row, host: dict,
                    dur_ms: float) -> None:
        """One decode tick: `dev_row` is the pulled TICK_FIELDS int32
        vector (None when the device fields are disabled), `host` the
        scheduler-side fields, `dur_ms` the tick's wall time (device
        dispatch + the shared pull)."""
        rec = {"kind": "serving_tick", "tick": int(tick),
               "t": time.time(), "dur_ms": round(float(dur_ms), 3)}
        if dev_row is not None:
            for f, v in zip(TICK_FIELDS, dev_row):
                rec[f] = int(v)
        rec.update(host)
        self._push(rec)

    def record_prefill(self, tick: int, dur_ms: float, chunk_len: int,
                       bucket: int, final: bool, slot: int) -> None:
        self._push({"kind": "serving_prefill", "tick": int(tick),
                    "t": time.time(), "dur_ms": round(float(dur_ms), 3),
                    "chunk_len": int(chunk_len), "bucket": int(bucket),
                    "final": bool(final), "slot": int(slot)})

    def _push(self, rec: dict) -> None:
        self._ring.append(rec)
        if self._writer is not None:
            self._pending.append(rec)
            if len(self._pending) >= self.every:
                # live memory gauges ride the batch drain (the serving
                # flush cadence): host-side reads only, zero extra
                # device pulls, so streams stay bit-identical to
                # telemetry-off
                from .mem_audit import publish_hbm_gauges
                publish_hbm_gauges()
                if self.on_flush is not None:
                    try:
                        self.on_flush()
                    except Exception:              # noqa: BLE001
                        pass       # gauges must never break the stream
                self._writer.put(self._pending)
                self._pending = []

    # ------------------------------------------------------------- access
    def records(self) -> list:
        """The in-memory ring (newest-last)."""
        return list(self._ring)

    def flush(self, timeout: Optional[float] = None) -> None:
        """Push any pending batch and block until it is on disk (no-op
        without a JSONL path)."""
        if self._writer is None:
            return
        if self._pending:
            self._writer.put(self._pending)
            self._pending = []
        self._writer.flush(timeout=timeout)

    def close(self) -> None:
        if self._writer is not None:
            self.flush(timeout=30)
            self._writer.close()
            self._writer = None
