"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, rebuilt on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `paddle.*` (reference: python/paddle/__init__.py)
so reference-shaped user code ports by changing the import. Heavy subpackages
load lazily (PEP 562).
"""
from __future__ import annotations

import importlib

from .version import full_version as __version__  # noqa: E402

from .framework import (
    Tensor, to_tensor, no_grad, enable_grad, is_grad_enabled,
    set_grad_enabled, seed, get_rng_state, set_rng_state,
    get_default_dtype, set_default_dtype,
    Place, TPUPlace, CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    CustomPlace,
)
from .framework import dtype as _dtype_mod
from .framework.dtype import (
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
from .framework.autograd import grad_fn_of as _grad_fn_of
from .framework.flags import set_flags, get_flags

from .tensor import *  # noqa: F401,F403 — flat tensor-function namespace
from . import tensor  # noqa: F401
from . import device  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda  # noqa: F401
from . import linalg  # noqa: F401

_LAZY_SUBMODULES = (
    "nn", "optimizer", "ops", "amp", "io", "jit", "autograd", "framework",
    "distributed", "parallel", "distribution", "vision", "audio", "text",
    "metric", "static", "inference", "profiler", "incubate", "sparse",
    "onnx", "hapi", "callbacks", "fft", "signal", "quantization", "utils",
    "regularizer", "sysconfig", "geometric", "hub", "cost_model", "pir",
    "models", "kernels",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi.model import Model
        globals()["Model"] = Model
        return Model
    if name == "DataParallel":
        from .parallel.data_parallel import DataParallel
        globals()["DataParallel"] = DataParallel
        return DataParallel
    if name == "Parameter":
        from .nn.parameter import Parameter
        globals()["Parameter"] = Parameter
        return Parameter
    if name == "ParamAttr":
        from .nn.param_attr import ParamAttr
        globals()["ParamAttr"] = ParamAttr
        return ParamAttr
    if name in ("save", "load"):
        from . import framework_io
        globals()["save"] = framework_io.save
        globals()["load"] = framework_io.load
        return globals()[name]
    if name == "summary":
        from .hapi.model_summary import summary
        globals()["summary"] = summary
        return summary
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


dtype = _dtype_mod.convert_dtype


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad analog (reference: python/paddle/autograd)."""
    return _grad_fn_of(outputs, inputs, grad_outputs, retain_graph,
                       create_graph, allow_unused)


def disable_static(place=None):
    """Back to dygraph (the default mode)."""
    from .static.program import disable_static as _ds
    _ds()


def enable_static():
    """Enter static-graph mode: ops record into the default Program and
    run via static.Executor (reference paddle.enable_static; see
    paddle_tpu/static/program.py for the TPU-native design)."""
    from .static.program import enable_static as _es
    _es()


def in_dynamic_mode():
    from .static.program import in_static_graph_mode
    return not in_static_graph_mode()


in_dygraph_mode = in_dynamic_mode


def _limits_dtype(d):
    """Resolve a dtype for limits queries WITHOUT jax canonicalization:
    iinfo('int64') must describe int64 even though x32 execution would
    lower it — the query is about the dtype, not the backend. Accepts
    everything np.dtype does (np scalar types, python int/float, dtype
    objects) plus extension-dtype names (bfloat16, float8_*)."""
    import numpy as _np
    try:
        return _np.dtype(d)
    except TypeError:
        pass
    name = (getattr(d, "name", None) or str(d)).split(".")[-1]
    try:
        return _np.dtype(name)
    except TypeError:
        import ml_dtypes
        return _np.dtype(getattr(ml_dtypes, name))


def iinfo(dtype):
    """Integer dtype limits (reference paddle.iinfo over numpy's)."""
    import numpy as _np
    return _np.iinfo(_limits_dtype(dtype))


def finfo(dtype):
    """Float dtype limits (reference paddle.finfo). bfloat16/float8 go
    through ml_dtypes.finfo (numpy's finfo rejects extension dtypes)."""
    import numpy as _np
    dt = _limits_dtype(dtype)
    try:
        return _np.finfo(dt)
    except ValueError:
        import ml_dtypes
        return ml_dtypes.finfo(dt)


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference paddle.batch /
    python/paddle/reader/decorator.py): wrap a sample generator into a
    batch generator."""
    if batch_size <= 0:
        raise ValueError(
            f"batch_size must be a positive integer, got {batch_size}")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Model forward FLOPs (reference paddle.flops / hapi dynamic_flops).
    TPU-native: XLA's own cost analysis counts the compiled forward —
    exact for the whole graph, no per-layer-type hook table needed
    (custom_ops therefore has no effect and warns).
    `input_size` is one shape list or a list of shapes."""
    if custom_ops:
        import warnings
        warnings.warn(
            "paddle_tpu.flops counts via XLA's cost analysis; custom_ops "
            "per-layer overrides are ignored", RuntimeWarning)
    import jax.numpy as _jnp
    from .profiler import cost_analysis
    from .framework.tensor import Tensor

    shapes = input_size if isinstance(input_size[0], (list, tuple)) \
        else [input_size]
    examples = [_jnp.zeros(tuple(s), _jnp.float32) for s in shapes]

    def fwd(*arrs):
        outs = net(*[Tensor(a) for a in arrs])
        import jax
        return [o._value if isinstance(o, Tensor) else o
                for o in jax.tree_util.tree_leaves(outs)]

    total = int(cost_analysis(fwd, *examples)["flops"])
    if print_detail:
        import builtins
        # builtins.sum: this module's namespace holds the paddle `sum` op
        n_params = builtins.sum(int(p.size) for p in net.parameters())
        print(f"Total Flops: {total}     Total Params: {n_params}")
    return total


def set_printoptions(**kwargs):
    import numpy as _np
    _np.set_printoptions(**{k: v for k, v in kwargs.items()
                            if k in ("precision", "threshold", "edgeitems",
                                     "linewidth")})
