"""paddle_tpu.fft — discrete Fourier transforms.

Reference analog: python/paddle/fft.py (paddle.fft namespace over the phi
fft_c2c / fft_r2c / fft_c2r kernels backed by pocketfft/cuFFT —
/root/reference/paddle/phi/kernels/funcs/fft.h). On TPU the transforms lower
to XLA's FFT HLO; every function routes through the dispatch layer so tape
gradients and to_static traces work like any other op.

Norm conventions match the reference ("backward" | "ortho" | "forward").
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .framework.dispatch import apply
from .framework.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(
            f"norm must be 'backward'|'ortho'|'forward', got {norm!r}")
    return norm


def _mk1d(opname, jfn):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        def _op(v, n, axis, norm):
            return jfn(v, n=n, axis=axis, norm=norm)
        return apply(opname, _op, x, n=None if n is None else int(n),
                     axis=int(axis), norm=_norm(norm))
    f.__name__ = opname
    f.__doc__ = f"Reference: paddle.fft.{opname} (phi fft kernels)."
    return f


def _mkNd(opname, jfn, default_axes):
    def f(x, s=None, axes=default_axes, norm="backward", name=None):
        def _op(v, s, axes, norm):
            return jfn(v, s=s, axes=axes, norm=norm)
        return apply(opname, _op, x,
                     s=None if s is None else tuple(int(v) for v in s),
                     axes=None if axes is None
                     else tuple(int(a) for a in axes),
                     norm=_norm(norm))
    f.__name__ = opname
    f.__doc__ = f"Reference: paddle.fft.{opname} (phi fft kernels)."
    return f


fft = _mk1d("fft", jnp.fft.fft)          # c2c
ifft = _mk1d("ifft", jnp.fft.ifft)
rfft = _mk1d("rfft", jnp.fft.rfft)       # r2c
irfft = _mk1d("irfft", jnp.fft.irfft)    # c2r
hfft = _mk1d("hfft", jnp.fft.hfft)
ihfft = _mk1d("ihfft", jnp.fft.ihfft)

def _hfftn_impl(v, s, axes, norm):
    """Hermitian-input n-D FFT. scipy relation: hfftn = hfft over the last
    axis of fftn over the leading axes (so ihfftn∘hfftn is identity and
    ihfftn(y) == conj(rfftn(y))/N). axes=None = all axes; s follows axes."""
    if axes is None:
        axes = tuple(range(v.ndim))
    s_list = [None] * len(axes) if s is None else list(s)
    if len(axes) > 1:
        lead = None if s is None else tuple(s_list[:-1])
        v = jnp.fft.fftn(v, s=lead, axes=axes[:-1], norm=norm)
    return jnp.fft.hfft(v, n=s_list[-1], axis=axes[-1], norm=norm)


def _ihfftn_impl(v, s, axes, norm):
    """ihfftn = ifftn over the leading axes of ihfft over the last axis
    (== conj(rfftn)/N, the scipy/paddle convention)."""
    if axes is None:
        axes = tuple(range(v.ndim))
    s_list = [None] * len(axes) if s is None else list(s)
    v = jnp.fft.ihfft(v, n=s_list[-1], axis=axes[-1], norm=norm)
    if len(axes) > 1:
        lead = None if s is None else tuple(s_list[:-1])
        v = jnp.fft.ifftn(v, s=lead, axes=axes[:-1], norm=norm)
    return v


fft2 = _mkNd("fft2", jnp.fft.fftn, (-2, -1))
ifft2 = _mkNd("ifft2", jnp.fft.ifftn, (-2, -1))
rfft2 = _mkNd("rfft2", jnp.fft.rfftn, (-2, -1))
irfft2 = _mkNd("irfft2", jnp.fft.irfftn, (-2, -1))
hfft2 = _mkNd("hfft2", _hfftn_impl, (-2, -1))
ihfft2 = _mkNd("ihfft2", _ihfftn_impl, (-2, -1))

fftn = _mkNd("fftn", jnp.fft.fftn, None)
ifftn = _mkNd("ifftn", jnp.fft.ifftn, None)
rfftn = _mkNd("rfftn", jnp.fft.rfftn, None)
irfftn = _mkNd("irfftn", jnp.fft.irfftn, None)
hfftn = _mkNd("hfftn", _hfftn_impl, None)
ihfftn = _mkNd("ihfftn", _ihfftn_impl, None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import to_tensor
    return to_tensor(np.fft.fftfreq(int(n), float(d)).astype(
        np.dtype(dtype) if dtype else np.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import to_tensor
    return to_tensor(np.fft.rfftfreq(int(n), float(d)).astype(
        np.dtype(dtype) if dtype else np.float32))


def fftshift(x, axes=None, name=None):
    def _op(v, axes):
        return jnp.fft.fftshift(v, axes=axes)
    return apply("fftshift", _op, x,
                 axes=None if axes is None else tuple(
                     int(a) for a in (axes if isinstance(axes, (list, tuple))
                                      else [axes])))


def ifftshift(x, axes=None, name=None):
    def _op(v, axes):
        return jnp.fft.ifftshift(v, axes=axes)
    return apply("ifftshift", _op, x,
                 axes=None if axes is None else tuple(
                     int(a) for a in (axes if isinstance(axes, (list, tuple))
                                      else [axes])))
