"""paddle_tpu.sysconfig (reference python/paddle/sysconfig.py:
get_include/get_lib for building extensions against the framework)."""
import os


def get_include() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "libs")
