"""paddle_tpu.callbacks — re-export of hapi.callbacks (the reference's
paddle.callbacks namespace, python/paddle/__init__.py)."""
from ..hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
    LRScheduler)
