"""Kernel autotune: timed-candidate selection with a persistent cache.

Reference analog: paddle/phi/kernels/autotune/ (cache.cc AlgorithmsCache +
switch_autotune.cc — time each conv algo once per signature, cache the
winner). TPU-native: the tunables are Pallas grid/block parameters; each
candidate costs a compile, so tuning is opt-in
(paddle_tpu.set_flags({'use_autotune': True}) or PADDLE_TPU_AUTOTUNE=1)
and winners persist to a JSON cache keyed by (op, signature) so the
compile cost is paid once per machine, not per process.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_CACHE: Dict[str, Any] = {}
_CACHE_PATH = os.environ.get(
    "PADDLE_TPU_AUTOTUNE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "autotune.json"))
_loaded = False
_stats = {"hits": 0, "misses": 0, "tuned": 0}


def enabled() -> bool:
    if os.environ.get("PADDLE_TPU_AUTOTUNE", "") in ("1", "true", "True"):
        return True
    from ..framework.flags import flag
    return bool(flag("use_autotune", False))


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(_CACHE_PATH) as f:
            _CACHE.update(json.load(f))
    except (OSError, ValueError):
        pass


def _persist():
    # tmp + os.replace: concurrent processes (multi-host launch) each write
    # a whole valid file and the last rename wins — never a torn JSON that
    # _load would silently discard
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        tmp = f"{_CACHE_PATH}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_CACHE, f, indent=1)
        os.replace(tmp, _CACHE_PATH)
    except OSError:
        pass


def _read(op: str, signature: str):
    """ONE home for the raw cache-entry semantics: returns
    ('hit', winner) with lists back as tuples, ('optout',) for a
    hand-edited empty entry (the documented "no tuned winner" escape
    hatch), or ('miss',)."""
    _load()
    hit = _CACHE.get(f"{op}::{signature}")
    if hit is None:
        return ("miss",)
    if isinstance(hit, list):
        return ("hit", tuple(hit)) if hit else ("optout",)
    return ("hit", hit)


def cached(op: str, signature: str):
    """Cache READ (no timing): a persisted winner — from a prior
    in-process tune or an offline tools/autotune_kernels.py sweep —
    applies even when live tuning is off (reference cache.cc reads
    unconditionally; switch_autotune only gates the timed pass).
    Returns the winner (lists back as tuples) or None."""
    state = _read(op, signature)
    return state[1] if state[0] == "hit" else None


def cached_any_batch(op: str, signature: str):
    """Batch-agnostic cache READ: exact signature first, then any entry
    for the same op whose signature differs only in the leading `B{n}_`
    batch field. Pallas block sizes tile the sequence/head dims, not the
    batch (batch is a grid axis), so a winner tuned at one batch is the
    right default at another when the exact key misses. An exact-key
    opt-out entry is honored: it never falls back to another batch."""
    state = _read(op, signature)
    if state[0] == "hit":
        return state[1]
    if state[0] == "optout":
        return None
    head, _, suffix = signature.partition("_")
    if not suffix:
        return None
    try:
        want_b = int(head[1:])
    except ValueError:
        return None
    # deterministic choice when several batches share the suffix: nearest
    # batch wins, key order breaks ties (cache write order must not
    # change which blocks a bench runs with)
    best = None
    for key in sorted(_CACHE):
        if not key.startswith(f"{op}::B"):
            continue
        sig = key.split("::", 1)[1]
        b_field, _, sig_suffix = sig.partition("_")
        state = _read(op, sig)
        if sig_suffix != suffix or state[0] != "hit":
            continue
        try:
            dist = abs(int(b_field[1:]) - want_b)
        except ValueError:
            continue
        if best is None or dist < best[0]:
            best = (dist, state[1])
    return best[1] if best else None


def autotune_status() -> dict:
    """Reference switch_autotune.cc status counters."""
    return dict(_stats, cached=len(_CACHE), enabled=enabled())


def clear_cache():
    _CACHE.clear()
    try:
        os.remove(_CACHE_PATH)
    except OSError:
        pass


def pick(op: str, signature: str, candidates: Sequence[Any],
         runner: Callable[[Any], None], default: Any = None,
         warmup: int = 1, iters: int = 3):
    """Return the fastest candidate for (op, signature).

    runner(candidate) must execute the kernel end-to-end (blocking). The
    winner is cached in-process and on disk; when tuning is disabled the
    cached winner (or `default`/first candidate) is returned without any
    timing."""
    state = _read(op, signature)
    if state[0] == "hit":
        _stats["hits"] += 1
        return state[1]
    # an explicit opt-out entry behaves exactly like a disabled tuner
    # for this signature
    if state[0] == "optout" or not enabled():
        _stats["misses"] += 1
        return default if default is not None else candidates[0]

    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            for _ in range(warmup):
                runner(cand)
            t0 = time.perf_counter()
            for _ in range(iters):
                runner(cand)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue                      # candidate invalid on this shape
        if dt < best_t:
            best, best_t = cand, dt
    if best is None:
        # nothing could be measured (e.g. transient backend failure):
        # return the default WITHOUT caching, so a later healthy run
        # re-tunes instead of freezing an unmeasured winner
        return default if default is not None else candidates[0]
    _CACHE[f"{op}::{signature}"] = (list(best) if isinstance(best, tuple)
                                    else best)
    _stats["tuned"] += 1
    _persist()
    return best


def flash_block_candidates(seq_q: int, seq_k: int) -> List[Tuple[int, int]]:
    """Legal (block_q, block_k) candidates for the flash kernels."""
    opts = [128, 256, 512]
    return [(bq, bk) for bq in opts for bk in opts
            if bq <= max(128, seq_q) and bk <= max(128, seq_k)]
