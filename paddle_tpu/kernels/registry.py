"""Evidence-gated kernel selection registry.

Reference analog: the autotune subsystem's cached algorithm choice
(paddle/phi/kernels/autotune/cache.cc:1 AlgorithmsCache +
switch_autotune.cc:1), generalized from per-shape block sizes
(kernels/autotune.py) to WHICH IMPLEMENTATION a selectable kernel ships
with: a persistent per-(kernel, backend-class, shape-bucket) winner
table in perf/kernel_registry.json.

Why a registry and not a fallback chain: the round-5 verdict found the
TPU attention default silently resolving to the homegrown Pallas kernel
— the one implementation the only hardware ablation measured as a net
loss (399.7 ms/step for xla vs 427.6+ for every Pallas forward) —
because the evidence lived in window artifacts nothing consulted. Here
the evidence IS the table: every `measured` entry carries the ms and
the arithmetic/memory volume that justify it, and `adopt()` refuses to
persist a row the roofline plausibility gate rejects — a single
tunnel-artifact-inflated sweep timing can never become the shipped
default (the round-4 failure mode BASELINE.md disavows).

Entry kinds:
- `measured`: impl + ms + flops/bytes evidence; must sit inside the
  physical window (`gate_ms` returns None) to load OR to be adopted.
- `policy`: impl + human reason, no perf claim — e.g. CPU keeps the
  homegrown Pallas attention so interpret-mode parity coverage keeps
  running in the test suite.

Selection precedence at the consult sites stays: explicit env override
> freshly-adopted sweep winner (attention, TPU only) > registry winner
> hardcoded default.

The roofline gate (plausible_ms / gate_ms) lives HERE so the package's
adoption path and the measurement tools share one rule;
tools/bench_util.py re-exports it for the existing tool callers.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional

# ---------------------------------------------------------------- gate
# Roofline anchors for the plausibility gate (v5-litepod class defaults;
# override via env for other parts).
PEAK_BF16_TFLOPS = float(os.environ.get("PADDLE_TPU_PEAK_TFLOPS", "197"))
PEAK_HBM_GBS = float(os.environ.get("PADDLE_TPU_PEAK_HBM_GBS", "819"))
# Below these effective rates a kernel-sized timing is measuring the
# tunnel/host, not the chip — the round-4 sweep persisted CE rows at
# 3.4-7.9 s for a ~15 ms kernel, which this floor rejects.
FLOOR_TFLOPS = 0.5
FLOOR_GBS = 20.0


def plausible_ms(flops: float = 0.0, bytes_moved: float = 0.0):
    """Physical window (lo_ms, hi_ms) for ONE application of a kernel of
    known arithmetic/memory volume. lo = half the roofline time (nothing
    runs 2x faster than the roofline); hi = the time implied by the
    FLOOR_* effective rates (anything slower is a measurement artifact,
    not a slow kernel)."""
    lo_s = max(flops / (PEAK_BF16_TFLOPS * 1e12),
               bytes_moved / (PEAK_HBM_GBS * 1e9)) / 2.0
    hi_s = max(flops / (FLOOR_TFLOPS * 1e12),
               bytes_moved / (FLOOR_GBS * 1e9), 1e-6)
    return lo_s * 1e3, hi_s * 1e3


def gate_ms(ms: float, flops: float = 0.0, bytes_moved: float = 0.0):
    """None if `ms` is physically plausible for the given volumes, else a
    short reason string for the record."""
    lo, hi = plausible_ms(flops, bytes_moved)
    if ms < lo:
        return f"implausibly fast: {ms:.3f} ms < {lo:.3f} ms (2x roofline)"
    if ms > hi:
        return (f"implausibly slow: {ms:.3f} ms > {hi:.1f} ms "
                "(sub-floor effective rate; likely RTT/host-bound)")
    return None


# ------------------------------------------------------------- registry
REGISTRY_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "perf", "kernel_registry.json")

# selectable kernels and their legal impl names — an entry naming
# anything else is invalid (catches a hand-edit typo before it silently
# falls through to the hardcoded default)
KNOWN_IMPLS: Dict[str, tuple] = {
    "attention": ("pallas", "jax_flash", "splash", "xla"),
    # 'pallas_fused' = the one-pass CE+grad kernel (pallas_ce.ce_fused_
    # train: backward collapses into the forward launch) — training
    # paths only; select via evidence-gated adoption, never by default
    "ce": ("pallas", "jax", "pallas_fused"),
    # fused AdamW/AMP master-update (kernels/pallas_update.py): 'jax' =
    # the models.gpt.apply_adamw tree-level form (default + oracle),
    # 'pallas' = the one-launch-per-leaf kernel;
    # tools/bench_fused_step.py --adopt is the evidence-gated writer
    "fused_update": ("jax", "pallas"),
    "varlen_attention": ("blockwise", "dense"),
    # decode-path attention over the KV cache (greedy decode + the
    # serving engine's slot pool): 'dense' = f32 scores/context (the
    # bit-parity default), 'mixed' = cache-dtype QK^T and P.V with an
    # f32 softmax (halves bf16 decode HBM traffic) — see
    # kernels/decode_attention.py
    "decode_attention": ("dense", "mixed"),
    # speculative decoding inside the serving tick (self-draft propose
    # + one-pass verify, inference/spec_decode.py): 'off' = one target
    # token per tick (the PR-4 shape), 'spec' = gamma-draft/verify
    # ticks. Env PADDLE_TPU_SPEC_DECODE overrides AND kill-switches;
    # tools/bench_serving.py --spec --adopt is the evidence-gated
    # writer
    "spec_decode": ("off", "spec"),
    # weight-only int8 serving (fused dequant-matmul over the stacked
    # serving weights, kernels/quant_matmul.py): 'off' = fp weights,
    # 'xla'/'pallas' = quantize at engine build and run the named
    # matmul impl. Env PADDLE_TPU_QUANT overrides AND kill-switches
    # (unrecognized values fail safe to off);
    # tools/bench_serving.py --quant --adopt is the evidence-gated
    # writer (refuses unless weight bytes <= 0.55x fp AND tokens/s
    # >= 0.95x fp)
    "quant_matmul": ("off", "xla", "pallas"),
    # fused multi-tick decode (inference/multi_tick.py): 'off' = one
    # decode tick per dispatch, 'scan' = K ticks inside one jitted
    # lax.scan with a device-side early-exit mask (one dispatch + one
    # host pull per K tokens — the chained_ms amortization in the
    # product path). Env PADDLE_TPU_MULTI_TICK overrides AND
    # kill-switches (an int >= 2 sets K; unrecognized fails safe to
    # off); tools/bench_serving.py --multi-tick --adopt is the
    # evidence-gated writer
    "multi_tick": ("off", "scan"),
}

_DOCS: Dict[str, Optional[dict]] = {}   # path -> parsed doc (memoized)


def backend_class(platform: Optional[str] = None) -> str:
    """'tpu' for TPU-class backends (real 'tpu' and the tunneled 'axon'
    plugin), 'cpu' for everything else. The registry buckets by CLASS,
    not platform string: a winner measured over the tunnel is the same
    chip as a directly-attached one."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    return "tpu" if platform in ("tpu", "axon") else "cpu"


def seq_bucket(n: int) -> str:
    """Power-of-two shape bucket for sequence-sized dims ('S1024').
    Winners generalize within a bucket; an exact-shape table would never
    get a hit outside the swept shapes."""
    b = 1
    while b < max(int(n), 1):
        b *= 2
    return f"S{b}"


def _key(kernel: str, backend: str, bucket: str) -> str:
    return f"{kernel}::{backend}::{bucket}"


def _load(path: Optional[str] = None) -> dict:
    path = path or REGISTRY_PATH
    if path not in _DOCS:
        try:
            with open(path) as f:
                _DOCS[path] = json.load(f)
        except (OSError, ValueError):
            _DOCS[path] = {}
    return _DOCS[path] or {}


def _reset() -> None:
    """Drop the memoized file reads (tests; a registry landing mid-process
    otherwise applies from the next process, like the sweep winner)."""
    _DOCS.clear()


def _entry_problem(key: str, ent) -> Optional[str]:
    """One entry's validation verdict: None when well-formed AND
    evidence-gated, else the reason. ONE rule for load-time trust,
    adopt-time gating and the CI check."""
    parts = key.split("::")
    if len(parts) != 3:
        return f"{key}: key is not kernel::backend::bucket"
    kernel, backend, _bucket = parts
    if backend not in ("tpu", "cpu"):
        return f"{key}: unknown backend class {backend!r}"
    if not isinstance(ent, dict):
        return f"{key}: entry is not an object"
    impl = ent.get("impl")
    legal = KNOWN_IMPLS.get(kernel)
    if legal is not None and impl not in legal:
        return f"{key}: impl {impl!r} not one of {legal}"
    kind = ent.get("kind")
    if kind == "policy":
        if not ent.get("reason"):
            return f"{key}: policy entry with no reason"
        return None
    if kind != "measured":
        return f"{key}: kind {kind!r} is neither measured nor policy"
    ms = ent.get("ms")
    flops = float(ent.get("flops", 0.0) or 0.0)
    bytes_moved = float(ent.get("bytes_moved", 0.0) or 0.0)
    if not isinstance(ms, (int, float)) or ms <= 0:
        return f"{key}: measured entry with no ms"
    if flops <= 0 and bytes_moved <= 0:
        return (f"{key}: measured entry carries no arithmetic/memory "
                "volume, so plausibility cannot be checked")
    reason = gate_ms(float(ms), flops=flops, bytes_moved=bytes_moved)
    if reason:
        return f"{key}: {reason}"
    return None


def validate(doc: Optional[dict] = None,
             path: Optional[str] = None) -> list:
    """Every problem in the registry file (empty list = clean). The CI
    check and the load path share this; an entry that fails here is
    never served by winner()."""
    if doc is None:
        doc = _load(path)
    return [p for key, ent in (doc.get("entries") or {}).items()
            for p in [_entry_problem(key, ent)] if p]


def winner(kernel: str, backend: Optional[str] = None,
           bucket: str = "*", path: Optional[str] = None) -> Optional[str]:
    """The registered impl for (kernel, backend-class, bucket), falling
    back from the exact bucket to the '*' wildcard; None when the table
    has no trustworthy row. Entries that fail validation are skipped —
    a hand-edited or corrupted row degrades to the hardcoded default
    instead of shipping."""
    from ..profiler import monitor
    backend = backend or backend_class()
    entries = _load(path).get("entries") or {}
    for b in dict.fromkeys((bucket, "*")):
        ent = entries.get(_key(kernel, backend, b))
        if ent is not None and _entry_problem(_key(kernel, backend, b),
                                              ent) is None:
            # which impl the registry actually served, per kernel — the
            # observable that caught the round-5 silent-default regression
            monitor.counter(
                f"kernel_registry_resolution.{kernel}."
                f"{ent.get('impl')}").add()
            return ent.get("impl")
    monitor.counter(f"kernel_registry_miss.{kernel}").add()
    return None


def entry(kernel: str, backend: str, bucket: str = "*",
          path: Optional[str] = None) -> Optional[dict]:
    """Raw entry read (inspection/tests); no validation applied."""
    return (_load(path).get("entries") or {}).get(
        _key(kernel, backend, bucket))


def adopt(kernel: str, impl: str, ms: float, flops: float = 0.0,
          bytes_moved: float = 0.0, backend: Optional[str] = None,
          bucket: str = "*", source: str = "", window: str = "",
          path: Optional[str] = None) -> Optional[str]:
    """Persist a measured winner — THE only write path, and it refuses
    anything the plausibility gate rejects. Returns None on success or
    the rejection reason (the caller logs it; the file is untouched).
    Atomic tmp+rename write, like the autotune cache."""
    backend = backend or backend_class()
    path = path or REGISTRY_PATH
    ent = {"impl": impl, "kind": "measured", "ms": round(float(ms), 3),
           "flops": float(flops), "bytes_moved": float(bytes_moved),
           "source": source, "window": window}
    key = _key(kernel, backend, bucket)
    problem = _entry_problem(key, ent)
    if problem:
        return problem
    doc = dict(_load(path))
    entries = dict(doc.get("entries") or {})
    entries[key] = ent
    doc["entries"] = entries
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        return f"registry write failed: {e}"
    _DOCS[path] = doc
    return None
