"""Hand-tiled Pallas TPU flash-attention forward kernel.

Reference analog: the external flash-attention CUDA library the reference
wires in via cmake/external/flashattn.cmake and exposes through
paddle/phi/kernels/gpu/flash_attn_kernel.cu. Here the kernel is written
TPU-first with Pallas: the score matmul and the PV matmul hit the MXU per
(block_q × block_k) tile, the online-softmax state (m, l, acc) lives in VMEM
scratch across the kv-block grid dimension, and HBM traffic is O(S·D) instead
of O(S²).

Layout convention matches the reference flash_attn API: [B, S, H, D].
The kernel internally works on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .primitives import (NEG_INF as _NEG_INF,
                         ROW_SCALAR_LANES, bounds_mask, causal_block_live,
                         causal_mask, env_block as _env_block,
                         logsumexp_finalize, online_softmax_update,
                         pad_to, softmax_finalize, tile_positions)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, kv_len):
    i = pl.program_id(1)          # q block
    j = pl.program_id(2)          # kv block (innermost: scratch carries over)
    nkv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _body():
        # operands stay in the input dtype (bf16 on the bench path) so
        # the MXU runs in its native mode; accumulation is f32 via
        # preferred_element_type, and the softmax scale is applied to the
        # f32 scores post-dot (exact, and off the matmul critical path)
        q = q_ref[0]                                        # (BQ, D)
        k = k_ref[0]                                        # (BK, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = tile_positions(j, block_k, (block_q, block_k), 1)
        valid = bounds_mask(kpos, kv_len)
        if causal:
            qpos = tile_positions(i, block_q, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, causal_mask(qpos, kpos))
        s = jnp.where(valid, s, _NEG_INF)

        m_new, l_new, p, corr = online_softmax_update(
            m_ref[:, :1], l_ref[:, :1], s)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip fully-masked kv blocks (upper-triangular block region)
        @pl.when(causal_block_live(i, j, block_q, block_k))
        def _():
            _body()
    else:
        _body()

    @pl.when(j == nkv - 1)
    def _finalize():
        o_ref[0] = softmax_finalize(acc_ref[...],
                                    l_ref[:, :1]).astype(o_ref.dtype)
        lse = logsumexp_finalize(m_ref[:, :1], l_ref[:, :1])
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])




def mha_fwd(q, k, v, causal=False, block_q=None, block_k=None,
            interpret=False, kv_len=None):
    """[B,S,H,D] → (out [B,S,H,D], lse [B,H,S]).  lse = m + log l, the
    softmax log-normalizer the jax-level flash backward recomputes p from.

    Thin non-jit wrapper: env block overrides resolve here so the jitted
    core's cache keys on the concrete block sizes."""
    bq = _env_block("PADDLE_TPU_FLASH_BLOCK_Q", 128) \
        if block_q is None else block_q
    bk = _env_block("PADDLE_TPU_FLASH_BLOCK_K", 128) \
        if block_k is None else block_k
    return _mha_fwd_jit(q, k, v, causal=causal, block_q=bq, block_k=bk,
                        interpret=interpret, kv_len=kv_len)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "kv_len"))
def _mha_fwd_jit(q, k, v, causal, block_q, block_k, interpret, kv_len):
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)

    # 128-aligned blocks: sublane/lane tiling is always legal and the
    # padding below absorbs any sequence length
    bq, bk = block_q, block_k
    q2 = pad_to(jnp.swapaxes(q, 1, 2).reshape(B * H, Sq, D), 1, bq)
    k2 = pad_to(jnp.swapaxes(k, 1, 2).reshape(B * H, Skv, D), 1, bk)
    v2 = pad_to(jnp.swapaxes(v, 1, 2).reshape(B * H, Skv, D), 1, bk)
    Sqp, Skp = q2.shape[1], k2.shape[1]
    grid = (B * H, Sqp // bq, Skp // bk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=Skv if kv_len is None else min(int(kv_len), Skv))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, ROW_SCALAR_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sqp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sqp, ROW_SCALAR_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),     # acc
            pltpu.VMEM((bq, 128), jnp.float32),   # m (lane-broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),   # l
        ],
        interpret=interpret,
    )(q2, k2, v2)

    out = jnp.swapaxes(out[:, :Sq].reshape(B, H, Sq, D), 1, 2)
    lse = lse[:, :Sq, 0].reshape(B, H, Sq)
    return out, lse


def mha(q, k, v, causal=False, interpret=False):
    out, _ = mha_fwd(q, k, v, causal=causal, interpret=interpret)
    return out


# ---------------------------------------------------------------- backward
# Two-pass design (the standard TPU flash backward): a dq kernel iterating
# kv blocks innermost with dq accumulating in VMEM scratch, and a dk/dv
# kernel iterating q blocks innermost with dk/dv in scratch. p is rebuilt
# per tile from the saved log-normalizer (lse), so backward HBM traffic is
# O(S·D) like the forward. delta = rowsum(do ⊙ out) is computed at the jax
# level (one fused elementwise pass).

def _mask_p(p, i, j, block_q, block_k, kv_len, causal):
    kpos = tile_positions(j, block_k, p.shape, 1)
    valid = bounds_mask(kpos, kv_len)
    if causal:
        qpos = tile_positions(i, block_q, p.shape, 0)
        valid = jnp.logical_and(valid, causal_mask(qpos, kpos))
    return jnp.where(valid, p, 0.0)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, kv_len):
    i = pl.program_id(1)          # q block
    j = pl.program_id(2)          # kv block (innermost: dq accumulates)
    nkv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body():
        # bf16 operands + f32 accumulation on every dot (MXU-native);
        # only the small elementwise ds/p math runs in f32 on the VPU
        q = q_ref[0]                                        # (BQ, D)
        k = k_ref[0]                                        # (BK, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0, :, :1])
        p = _mask_p(p, i, j, block_q, block_k, kv_len, causal)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1])
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(causal_block_live(i, j, block_q, block_k))
        def _():
            _body()
    else:
        _body()

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k, kv_len):
    j = pl.program_id(1)          # kv block
    i = pl.program_id(2)          # q block (innermost: dk/dv accumulate)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0]                                        # (BQ, D)
        k = k_ref[0]                                        # (BK, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0, :, :1])                  # (BQ, BK)
        p = _mask_p(p, i, j, block_q, block_k, kv_len, causal)
        do = do_ref[0]                                      # (BQ, D)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1])                 # (BQ, BK)
        # dk = scale · dsᵀ·q — scale folded in at finalize (f32, exact)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(causal_block_live(i, j, block_q, block_k))
        def _():
            _body()
    else:
        _body()

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def mha_bwd(q, k, v, out, lse, do, causal=False, block_q=None, block_k=None,
            interpret=False, kv_len=None):
    """Flash-attention backward. q/k/v/out/do [B,S,H,D], lse [B,H,S] from
    mha_fwd → (dq, dk, dv) in the input dtypes. Env blocks resolve here,
    outside the jitted core (see _env_block)."""
    bq = _env_block("PADDLE_TPU_FLASH_BLOCK_BWD_Q", 128) \
        if block_q is None else block_q
    bk = _env_block("PADDLE_TPU_FLASH_BLOCK_BWD_K", 128) \
        if block_k is None else block_k
    return _mha_bwd_jit(q, k, v, out, lse, do, causal=causal, block_q=bq,
                        block_k=bk, interpret=interpret, kv_len=kv_len)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "kv_len"))
def _mha_bwd_jit(q, k, v, out, lse, do, causal, block_q, block_k,
                 interpret, kv_len):
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq, bk = block_q, block_k

    q2 = pad_to(jnp.swapaxes(q, 1, 2).reshape(B * H, Sq, D), 1, bq)
    do2 = pad_to(jnp.swapaxes(do, 1, 2).reshape(B * H, Sq, D), 1, bq)
    k2 = pad_to(jnp.swapaxes(k, 1, 2).reshape(B * H, Skv, D), 1, bk)
    v2 = pad_to(jnp.swapaxes(v, 1, 2).reshape(B * H, Skv, D), 1, bk)
    # delta = rowsum(do ⊙ out): one fused elementwise+reduce pass in XLA
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), -1)
    delta = jnp.swapaxes(delta, 1, 2).reshape(B * H, Sq)  # via [B,S,H]->[B,H,S]
    # lse pad must kill padded q rows' p (exp(s - BIG) = 0) so they don't
    # pollute dk/dv; delta pad value is then irrelevant (ds = p * (...) = 0)
    lse2 = pad_to(lse.reshape(B * H, Sq, 1), 1, bq)
    lse2 = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, lse2.shape, 1) < Sq,
        lse2, jnp.float32(1e30))
    lse2 = jnp.broadcast_to(lse2, (B * H, lse2.shape[1], ROW_SCALAR_LANES))
    delta2 = jnp.broadcast_to(
        pad_to(delta.reshape(B * H, Sq, 1), 1, bq),
        (B * H, lse2.shape[1], ROW_SCALAR_LANES))

    Sqp, Skp = q2.shape[1], k2.shape[1]
    klen = Skv if kv_len is None else min(int(kv_len), Skv)

    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  kv_len=klen)
    in_arrs = (q2, k2, v2, do2, lse2, delta2)

    def _qspec(ix):
        return pl.BlockSpec((1, bq, D), ix)

    def _kspec(ix):
        return pl.BlockSpec((1, bk, D), ix)

    def _lspec(ix):
        return pl.BlockSpec((1, bq, ROW_SCALAR_LANES), ix)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * H, Sqp // bq, Skp // bk),
        in_specs=[
            _qspec(lambda b, i, j: (b, i, 0)),
            _kspec(lambda b, i, j: (b, j, 0)),
            _kspec(lambda b, i, j: (b, j, 0)),
            _qspec(lambda b, i, j: (b, i, 0)),
            _lspec(lambda b, i, j: (b, i, 0)),
            _lspec(lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sqp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*in_arrs)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B * H, Skp // bk, Sqp // bq),
        in_specs=[
            _qspec(lambda b, j, i: (b, i, 0)),
            _kspec(lambda b, j, i: (b, j, 0)),
            _kspec(lambda b, j, i: (b, j, 0)),
            _qspec(lambda b, j, i: (b, i, 0)),
            _lspec(lambda b, j, i: (b, i, 0)),
            _lspec(lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Skp, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Skp, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*in_arrs)

    dq = jnp.swapaxes(dq[:, :Sq].reshape(B, H, Sq, D), 1, 2)
    dk = jnp.swapaxes(dk[:, :Skv].reshape(B, H, Skv, D), 1, 2)
    dv = jnp.swapaxes(dv[:, :Skv].reshape(B, H, Skv, D), 1, 2)
    return dq, dk, dv
