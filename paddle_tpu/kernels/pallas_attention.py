"""Hand-tiled Pallas TPU flash-attention forward kernel.

Reference analog: the external flash-attention CUDA library the reference
wires in via cmake/external/flashattn.cmake and exposes through
paddle/phi/kernels/gpu/flash_attn_kernel.cu. Here the kernel is written
TPU-first with Pallas: the score matmul and the PV matmul hit the MXU per
(block_q × block_k) tile, the online-softmax state (m, l, acc) lives in VMEM
scratch across the kv-block grid dimension, and HBM traffic is O(S·D) instead
of O(S²).

Layout convention matches the reference flash_attn API: [B, S, H, D].
The kernel internally works on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
# lse is a scalar per q row; store it 8 lanes wide (min f32 sublane tile is
# (8,128) in VMEM regardless, but HBM traffic/storage shrink 16x vs 128 lanes)
_LSE_LANES = 8


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, kv_len):
    i = pl.program_id(1)          # q block
    j = pl.program_id(2)          # kv block (innermost: scratch carries over)
    nkv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale            # (BQ, D)
        k = k_ref[0].astype(jnp.float32)                    # (BK, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = kpos < kv_len
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_ref[:, :1]                               # (BQ, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip fully-masked kv blocks (upper-triangular block region)
        @pl.when(j * block_k <= i * block_q + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(j == nkv - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(jnp.maximum(l_ref[:, :1], 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "kv_len"))
def mha_fwd(q, k, v, causal=False, block_q=128, block_k=128, interpret=False,
            kv_len=None):
    """[B,S,H,D] → (out [B,S,H,D], lse [B,H,S]).  lse = m + log l, the
    softmax log-normalizer the jax-level flash backward recomputes p from."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)

    # fixed 128-aligned blocks: sublane/lane tiling is always legal and the
    # padding below absorbs any sequence length
    bq, bk = block_q, block_k
    q2 = _pad_to(jnp.swapaxes(q, 1, 2).reshape(B * H, Sq, D), 1, bq)
    k2 = _pad_to(jnp.swapaxes(k, 1, 2).reshape(B * H, Skv, D), 1, bk)
    v2 = _pad_to(jnp.swapaxes(v, 1, 2).reshape(B * H, Skv, D), 1, bk)
    Sqp, Skp = q2.shape[1], k2.shape[1]
    grid = (B * H, Sqp // bq, Skp // bk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=Skv if kv_len is None else min(int(kv_len), Skv))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sqp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sqp, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),     # acc
            pltpu.VMEM((bq, 128), jnp.float32),   # m (lane-broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),   # l
        ],
        interpret=interpret,
    )(q2, k2, v2)

    out = jnp.swapaxes(out[:, :Sq].reshape(B, H, Sq, D), 1, 2)
    lse = lse[:, :Sq, 0].reshape(B, H, Sq)
    return out, lse


def mha(q, k, v, causal=False, interpret=False):
    out, _ = mha_fwd(q, k, v, causal=causal, interpret=interpret)
    return out
