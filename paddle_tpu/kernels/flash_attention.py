"""Flash attention for TPU.

Reference analog: the external flashattn CUDA lib wired via
cmake/external/flashattn.cmake + phi flash_attn kernels
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu).

Two forward paths behind one entry:
- Pallas hand-tiled kernel (pallas_attention.mha_fwd) when the backend is TPU;
- a blockwise online-softmax lax.scan path that XLA fuses, used on CPU and as
  the safety net.

Both return the softmax log-normalizer (lse), and the backward is the
standard flash-attention recompute pass written at the jax level (scan over
kv blocks, f32): p is rebuilt from lse, so no O(S²) tensor is ever saved.
Wired via jax.custom_vjp, so the eager tape, jit.to_static and grad
transforms all pick up the memory-efficient backward.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..framework.dispatch import defop

_BLOCK_KV = 512


def available() -> bool:
    return True


def _dense_attention_lse(q, k, v, causal, kv_len=None):
    """O(S²) dense softmax attention. [B,S,H,D] → (out, lse [B,H,S]).
    kv_len: number of valid kv positions (suffix is masked), default all."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhsd,bhtd->bhst", qt, kt)
    if kv_len is not None and kv_len < Skv:
        s = jnp.where(jnp.arange(Skv)[None, :] < kv_len, s, -jnp.inf)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((Sq, Skv), bool)), s, -jnp.inf)
    m = jnp.max(s, -1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, -1)
    out = jnp.einsum("bhst,bhtd->bhsd", p / l[..., None], vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), m + jnp.log(l)


def _dense_reference(q, k, v, causal, kv_len=None):
    """O(S²) reference (testing / tiny shapes). [B,S,H,D]."""
    return _dense_attention_lse(q, k, v, causal, kv_len)[0]


def _blockwise_attention_lse(q, k, v, causal, kv_len=None):
    """Online-softmax attention over KV blocks. [B,S,H,D] → (out, lse)."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    # operands keep their dtype (bf16 stays MXU-native); scores/state
    # accumulate in f32 via preferred_element_type
    qt = jnp.swapaxes(q, 1, 2)                              # B,H,Sq,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    blk = min(_BLOCK_KV, Skv)
    if Skv % blk != 0:
        return _dense_attention_lse(q, k, v, causal, kv_len)

    nblk = Skv // blk
    kb = kt.reshape(B, H, nblk, blk, D)
    vb = vt.reshape(B, H, nblk, blk, D)
    q_pos = jnp.arange(Sq)

    def step(carry, inputs):
        m, l, acc = carry
        kblk, vblk, blk_idx = inputs
        scores = jnp.einsum("bhsd,bhtd->bhst", qt, kblk,
                            preferred_element_type=jnp.float32) * scale
        kv_pos = blk_idx * blk + jnp.arange(blk)
        if kv_len is not None and kv_len < Skv:
            scores = jnp.where(kv_pos[None, :] < kv_len, scores, -jnp.inf)
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask, scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        correction = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
        correction = jnp.where(jnp.isneginf(m), 0.0, correction)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + \
            jnp.einsum("bhst,bhtd->bhsd", p.astype(vblk.dtype), vblk,
                       preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    # carries derive from inputs so shard_map varying-axes tracking
    # matches; m/l/acc state is f32 regardless of input dtype
    m0 = jnp.full_like(qt[..., 0], -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros_like(qt[..., 0], dtype=jnp.float32)
    acc0 = jnp.zeros_like(qt, dtype=jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        step, (m0, l0, acc0),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0), jnp.arange(nblk)))
    l_safe = jnp.maximum(l, 1e-37)
    out = acc / l_safe[..., None]
    lse = jnp.where(jnp.isneginf(m), -jnp.inf, m + jnp.log(l_safe))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


# Kernel selection: the Pallas path runs on TPU-class backends ('tpu', and
# the tunneled 'axon' plugin) unless disabled. A Mosaic compile failure
# under an outer jit cannot be caught by try/except (it fires at top-level
# compile time), so selection is an explicit gate, not a fallback:
# - module global `use_pallas = False` (programmatic), or
# - env PADDLE_TPU_DISABLE_PALLAS=1 (operational escape hatch, re-read per
#   trace so a failed compile can be retried without editing code).
use_pallas = True


def _pallas_enabled() -> bool:
    import os
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS", "") in ("1", "true",
                                                           "True"):
        return False
    return use_pallas


def _pallas_attn_enabled(seq: int | None = None) -> bool:
    """Attention-only gate layered on the global one (CE kernel
    unaffected — it gates through _pallas_enabled directly): the round-4
    ablation measured the XLA attention path faster than the Pallas flash
    forward at S=1024, so benches race the two per-shape via
    PADDLE_TPU_DISABLE_PALLAS_ATTN."""
    import os
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_ATTN", "") in (
            "1", "true", "True"):
        return False
    if _attn_impl(seq) == "xla":
        return False
    return _pallas_enabled()


def _flash_sig(q, k, causal):
    B, Sq, H, D = q.shape
    return f"B{B}_Sq{Sq}_Sk{k.shape[1]}_H{H}_D{D}_c{int(causal)}_{q.dtype}"


def _env_blocks_set(*names) -> bool:
    """Explicit PADDLE_TPU_FLASH_BLOCK_* env overrides outrank the
    autotune cache — they are the operator's (and the block sweep's) way
    of forcing a size the cache would otherwise shadow."""
    import os
    return any(os.environ.get(n) for n in names)


def _tuned_blocks_bwd(q, k, causal):
    """Backward block sizes from the cache (populated by the offline
    sweep); batch-agnostic fallback; None = env/defaults."""
    if _env_blocks_set("PADDLE_TPU_FLASH_BLOCK_BWD_Q",
                       "PADDLE_TPU_FLASH_BLOCK_BWD_K"):
        return None
    from .autotune import cached_any_batch
    return cached_any_batch("flash_bwd", _flash_sig(q, k, causal))


def _tuned_blocks(q, k, causal):
    """Pick flash forward block sizes through the autotune cache
    (kernels/autotune.py — reference autotune/cache.cc); cache hits apply
    always, a timed tuning pass additionally runs when autotune is
    enabled; None = kernel defaults / env overrides."""
    from . import autotune
    if _env_blocks_set("PADDLE_TPU_FLASH_BLOCK_Q",
                       "PADDLE_TPU_FLASH_BLOCK_K"):
        return None
    sig = _flash_sig(q, k, causal)
    hit = autotune.cached_any_batch("flash_fwd", sig)
    if hit is not None:
        return hit
    if not autotune.enabled():
        return None
    from .pallas_attention import mha_fwd
    B, Sq, H, D = q.shape
    if isinstance(q, jax.core.Tracer):
        # Inside a trace (the normal path: eager dispatch jits every op,
        # and models run under jit) the tracers can't be timed — but
        # CONCRETE dummies of the same shape/dtype can: timing them here
        # runs eagerly while the outer trace is being built, i.e. tuning
        # happens once at compile time per signature (the reference's
        # switch_autotune does the same one-off timed pass). Shapes under
        # jit are static ints; bail to defaults if not (shape-polymorphic
        # export).
        try:
            shape_q = tuple(int(s) for s in q.shape)
            shape_k = tuple(int(s) for s in k.shape)
        except TypeError:
            return None       # polymorphic shape: cache already missed
        q_c = jnp.zeros(shape_q, q.dtype)
        k_c = jnp.zeros(shape_k, k.dtype)
    else:
        q_c, k_c = q, k

    def runner(cand):
        bq, bk = cand
        out, lse = mha_fwd(q_c, k_c, k_c, causal=causal, block_q=bq,
                           block_k=bk)
        # block_until_ready is unreliable over the axon tunnel; a scalar
        # device_get genuinely waits (same forcing bench.py uses)
        import numpy as _np
        _np.asarray(jax.device_get(out[(0,) * out.ndim]))
    return autotune.pick(
        "flash_fwd", sig, autotune.flash_block_candidates(Sq, k.shape[1]),
        runner, default=(128, 128))


def _fwd_with_lse(q, k, v, causal, kv_len=None):
    if _pallas_attn_enabled(q.shape[1]) \
            and jax.default_backend() in ("tpu", "axon"):
        from .pallas_attention import mha_fwd
        blocks = _tuned_blocks(q, k, causal)
        if blocks is not None:
            return mha_fwd(q, k, v, causal=causal, kv_len=kv_len,
                           block_q=blocks[0], block_k=blocks[1])
        return mha_fwd(q, k, v, causal=causal, kv_len=kv_len)
    return _blockwise_attention_lse(q, k, v, causal, kv_len)


def _flash_bwd(q, k, v, out, lse, do, causal, kv_len=None):
    """Flash-attention backward: recompute p per kv block from lse.

    delta = rowsum(do ⊙ out);  ds = p ⊙ (do·vᵀ − delta) · scale
    dq = Σ_j ds_j k_j ;  dk_j = ds_jᵀ q ;  dv_j = p_jᵀ do
    """
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    # operands keep their dtype (bf16 stays MXU-native); every einsum
    # accumulates f32 via preferred_element_type, and ds drops back to
    # the input dtype before its two dots — the standard mixed-precision
    # flash backward
    qt = jnp.swapaxes(q, 1, 2)                              # B,H,Sq,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    ot = jnp.swapaxes(out, 1, 2).astype(jnp.float32)
    dot_ = jnp.swapaxes(do, 1, 2)
    delta = jnp.sum(dot_.astype(jnp.float32) * ot, axis=-1)  # B,H,Sq

    blk = min(_BLOCK_KV, Skv)
    if Skv % blk != 0:
        blk = Skv
    nblk = Skv // blk
    kb = jnp.moveaxis(kt.reshape(B, H, nblk, blk, D), 2, 0)
    vb = jnp.moveaxis(vt.reshape(B, H, nblk, blk, D), 2, 0)
    q_pos = jnp.arange(Sq)

    def step(dq, inputs):
        kblk, vblk, blk_idx = inputs
        s = jnp.einsum("bhsd,bhtd->bhst", qt, kblk,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[..., None])                     # B,H,Sq,blk
        kv_pos = blk_idx * blk + jnp.arange(blk)
        if kv_len is not None and kv_len < Skv:
            p = jnp.where(kv_pos[None, :] < kv_len, p, 0.0)
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
            p = jnp.where(mask, p, 0.0)
        dv_j = jnp.einsum("bhst,bhsd->bhtd", p.astype(dot_.dtype), dot_,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhsd,bhtd->bhst", dot_, vblk,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(qt.dtype)
        dq = dq + jnp.einsum("bhst,bhtd->bhsd", ds, kblk,
                             preferred_element_type=jnp.float32)
        dk_j = jnp.einsum("bhst,bhsd->bhtd", ds, qt,
                          preferred_element_type=jnp.float32)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qt, dtype=jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        step, dq0, (kb, vb, jnp.arange(nblk)))
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(B, H, Skv, D)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(B, H, Skv, D)
    return (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_mha(q, k, v, causal, kv_len=None):
    out, _ = _fwd_with_lse(q, k, v, causal, kv_len)
    return out


def _flash_mha_fwd(q, k, v, causal, kv_len=None):
    out, lse = _fwd_with_lse(q, k, v, causal, kv_len)
    return out, (q, k, v, out, lse)


def _pallas_bwd_enabled(seq: int | None = None) -> bool:
    import os
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_BWD", "") in ("1", "true",
                                                               "True"):
        return False
    return _pallas_attn_enabled(seq)


def _flash_mha_bwd(causal, kv_len, res, do):
    q, k, v, out, lse = res
    if _pallas_bwd_enabled(q.shape[1]) \
            and jax.default_backend() in ("tpu", "axon"):
        from .pallas_attention import mha_bwd
        blocks = _tuned_blocks_bwd(q, k, causal)
        if blocks is not None:
            return mha_bwd(q, k, v, out, lse, do, causal=causal,
                           kv_len=kv_len, block_q=blocks[0],
                           block_k=blocks[1])
        return mha_bwd(q, k, v, out, lse, do, causal=causal, kv_len=kv_len)
    return _flash_bwd(q, k, v, out, lse, do, causal, kv_len)


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


_sweep_winner_impl = None     # memoized perf/sweep_winner.json read


def impl_from_winner_env(env: dict) -> str:
    """ONE home for the sweep-spec env -> impl translation (bench.py's
    race seeding uses it too): the sweep spells 'xla' as the
    PADDLE_TPU_DISABLE_PALLAS_ATTN kill switch. '' when the env names no
    recognizable impl."""
    impl = env.get("PADDLE_TPU_ATTN_IMPL", "")
    if not impl and env.get("PADDLE_TPU_DISABLE_PALLAS_ATTN") == "1":
        impl = "xla"
    return impl if impl in ("pallas", "jax_flash", "splash", "xla") \
        else ""


def _winner_impl():
    """Attention impl adopted by the latest hardware sweep
    (perf/sweep_winner.json, written by tools/tpu_campaign.py when the
    sweep job lands) — the measured winner ships as the TPU default
    without a code edit. Only consulted on TPU-class backends: the CPU
    suite must keep exercising the documented 'pallas' path (interpret-
    mode parity coverage would silently vanish otherwise). Memoized for
    the process lifetime; absent/invalid file -> None."""
    global _sweep_winner_impl
    if jax.default_backend() not in ("tpu", "axon"):
        return None
    if _sweep_winner_impl is None:
        import json
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "perf",
            "sweep_winner.json")
        env = {}
        try:
            with open(path) as f:
                env = json.load(f).get("env", {})
        except (OSError, ValueError):
            pass
        _sweep_winner_impl = impl_from_winner_env(env)
    return _sweep_winner_impl or None


def _registry_impl(seq: int | None = None):
    """Evidence-gated registry winner for the current backend class
    (kernels/registry.py; perf/kernel_registry.json). Seeded so that
    TPU-class backends default to 'xla' — the only hardware ablation's
    winner — and CPU keeps 'pallas' for parity coverage. Exact
    shape bucket first, then the wildcard row."""
    from . import registry
    cls = registry.backend_class(jax.default_backend())
    bucket = registry.seq_bucket(seq) if seq else "*"
    return registry.winner("attention", backend=cls, bucket=bucket)


def _attn_impl(seq: int | None = None) -> str:
    """Attention implementation selector (PADDLE_TPU_ATTN_IMPL):
    - 'pallas'   homegrown kernel + the gates above
    - 'jax_flash' jax.experimental.pallas.ops.tpu.flash_attention — the
      upstream-tuned TPU kernel with its own fwd+bwd Pallas passes
    - 'splash'   jax.experimental splash attention (block-sparse mask
      pipeline; usually the fastest causal kernel)
    - 'xla'      the blockwise lax.scan path (same as the ATTN kill)
    The ENV VAR is re-read per trace like the kill switches; with it
    unset, TPU-class backends follow the latest measured sweep winner
    (perf/sweep_winner.json, memoized per process — a sweep landing
    mid-process applies from the next process), then BOTH backend
    classes consult the kernel-selection registry
    (perf/kernel_registry.json, evidence-gated), and only then the
    hardcoded 'pallas'. `seq` (when the caller knows it) picks the
    registry's shape bucket."""
    import os
    return (os.environ.get("PADDLE_TPU_ATTN_IMPL")
            or _winner_impl() or _registry_impl(seq) or "pallas")


def _jax_flash_mha(q, k, v, causal):
    """The upstream TPU flash kernel ([B,H,S,D] layout, own custom_vjp —
    backward runs its dq/dkv Pallas kernels, not ours)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as tpu_flash)
    D = q.shape[-1]
    out = tpu_flash(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                    jnp.swapaxes(v, 1, 2), causal=causal,
                    sm_scale=1.0 / math.sqrt(D))
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=16)
def _splash_kernel(num_heads, seq_q, seq_k, causal, interpret=False):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)
    mk = (sm.CausalMask if causal else sm.FullMask)
    mask = sm.MultiHeadMask(
        [mk((seq_q, seq_k)) for _ in range(num_heads)])
    return sk.make_splash_mha_single_device(mask=mask, interpret=interpret)


def _splash_mha(q, k, v, causal, interpret=False):
    """The upstream splash-attention kernel: block-sparse mask pipeline
    that skips masked tiles at the grid level (newer than flash_attention
    and usually faster on long causal sequences). Single-device form,
    vmapped over batch; q is pre-scaled (splash has no sm_scale)."""
    B, S, H, D = q.shape
    kernel = _splash_kernel(H, S, k.shape[1], causal, interpret)
    scaled_q = jnp.swapaxes(q, 1, 2) * (1.0 / math.sqrt(D))
    out = jax.vmap(kernel)(scaled_q, jnp.swapaxes(k, 1, 2),
                           jnp.swapaxes(v, 1, 2))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _dispatch_mha(q, k, v, causal):
    # the upstream kernel is still Pallas: the global and attention kill
    # switches outrank the impl selector, preserving the documented
    # global > attention-only > impl layering
    impl = _attn_impl(q.shape[1])
    if (impl in ("jax_flash", "splash") and _pallas_attn_enabled(q.shape[1])
            and jax.default_backend() in ("tpu", "axon")):
        fn = _splash_mha if impl == "splash" else _jax_flash_mha
        return fn(q, k, v, causal)
    # 'xla' needs no branch here: _pallas_attn_enabled() reads the impl
    # and routes _flash_mha onto the blockwise fwd + jax-level bwd
    return _flash_mha(q, k, v, causal)


@defop("flash_attention_kernel")
def _flash_attention_op(q, k, v, causal):
    return _dispatch_mha(q, k, v, causal)


def flash_attention(q, k, v, causal=False):
    """[B,S,H,D] attention. Tensor-level entry used by nn.functional."""
    return _flash_attention_op(q, k, v, bool(causal))


def flash_attention_fn(q, k, v, causal=False):
    """Raw jax-level entry (for models that work on arrays, e.g. models.gpt)."""
    return _dispatch_mha(q, k, v, bool(causal))

