"""Flash attention for TPU.

Reference analog: the external flashattn CUDA lib wired via
cmake/external/flashattn.cmake + phi flash_attn kernels
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu).

Round-1 implementation: a blockwise-softmax (online softmax) attention written
with lax.scan over KV blocks — O(S) memory like flash attention, fully
XLA-fusable, works on TPU and CPU. A hand-tiled Pallas kernel slots in behind
the same entry point (see pallas_flash_attention below) and is used when the
backend is TPU and shapes meet its tiling constraints.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..framework.dispatch import defop

_BLOCK_KV = 512


def available() -> bool:
    return True


def _blockwise_attention(q, k, v, causal):
    """Online-softmax attention, scanning KV blocks. Layout: [B,S,H,D]."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # B,H,Sq,D
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)

    blk = min(_BLOCK_KV, Skv)
    if Skv % blk != 0:
        # fall back to dense for awkward sizes
        scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt)
        if causal:
            scores = jnp.where(jnp.tril(jnp.ones((Sq, Skv), bool)), scores,
                               -jnp.inf)
        out = jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(scores, -1), vt)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    nblk = Skv // blk
    kb = kt.reshape(B, H, nblk, blk, D)
    vb = vt.reshape(B, H, nblk, blk, D)
    q_pos = jnp.arange(Sq)

    def step(carry, inputs):
        m, l, acc = carry
        kblk, vblk, blk_idx = inputs
        scores = jnp.einsum("bhsd,bhtd->bhst", qt, kblk)
        if causal:
            kv_pos = blk_idx * blk + jnp.arange(blk)
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask, scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        correction = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
        correction = jnp.where(jnp.isneginf(m), 0.0, correction)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + \
            jnp.einsum("bhst,bhtd->bhsd", p, vblk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, Sq), -jnp.inf)
    l0 = jnp.zeros((B, H, Sq))
    acc0 = jnp.zeros((B, H, Sq, D))
    (m, l, acc), _ = jax.lax.scan(
        step, (m0, l0, acc0),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0), jnp.arange(nblk)))
    out = acc / jnp.maximum(l[..., None], 1e-37)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@defop("flash_attention_kernel")
def _flash_attention_op(q, k, v, causal):
    if jax.default_backend() == "tpu":
        try:
            return pallas_flash_attention(q, k, v, causal=causal)
        except Exception:
            pass
    return _blockwise_attention(q, k, v, causal)


def flash_attention(q, k, v, causal=False):
    """[B,S,H,D] attention. Tensor-level entry used by nn.functional."""
    return _flash_attention_op(q, k, v, bool(causal))


# ---------------------------------------------------------------------------
# Pallas TPU kernel (filled in by paddle_tpu.kernels round work); the jax-level
# blockwise path above is the portable fallback with the same math.
# ---------------------------------------------------------------------------
def pallas_flash_attention(q, k, v, causal=False):
    from .pallas_attention import mha as _mha
    return _mha(q, k, v, causal=causal)
