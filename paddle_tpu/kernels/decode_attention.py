"""Decode-path attention over a KV cache — the shared seam for every
cached forward (greedy decode, the continuous-batching serving engine).

Reference analog: the FusedMultiTransformer decode attention
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu:29, the
masked single-step branch) reached via
incubate/nn/layer/fused_transformer.py:1022. TPU-native collapse: at
T=1 the attention is a bandwidth-bound matvec over the cache — flash
tiling buys nothing — so the implementations here are dense masked
einsums; what stays selectable is the precision trade.

One implementation serves BOTH cache-position shapes:
- scalar `pos` — the whole batch sits at one position (whole-batch
  greedy decode, models/decode.py);
- per-row `pos` [B] — every row advances independently (the serving
  engine's slot pool, inference/serving.py: requests join and leave
  mid-decode, so slot i holds `pos[i]` tokens). T may exceed 1 here:
  the speculative verify pass (inference/spec_decode.py) runs the
  current token + gamma drafts as one [B, gamma+1] step — the mask
  stays per-query-position causal, and multi-token per-row writes
  drop (never clamp) positions past the cache end.

GQA is native: kc/vc carry KV heads; queries fold their group axis into
the einsum so repeated KV is never materialized (models/llama.py's
decode-bandwidth trade).

Implementation selection (the kernels/registry.py seam — env >
registry winner > default, same precedence as flash_attention._attn_impl):
- 'dense'  f32 scores AND f32 context accumulation (default: exactly
  the training forward's numerics, required for the serving engine's
  bit-parity guarantee against per-request greedy decode);
- 'mixed'  QK^T and P·V run in the cache dtype with an f32 softmax —
  halves decode HBM traffic for bf16 caches; opt in per backend via
  the registry or PADDLE_TPU_DECODE_ATTN_IMPL;
- 'paged'  the serving engine's block-pool cache layout (vLLM's
  PagedAttention, SOSP '23): K/V live in fixed-size pages
  [P, page_size, KV, hd] shared by every slot, and a per-slot page
  table [B, max_pages] maps logical cache positions to physical
  pages. `gather_pages` re-linearizes a slot's view (logical position
  p lands at view index p, so the attention math — and therefore the
  token stream — is BIT-IDENTICAL to 'dense'); `write_kv_paged`
  scatters the step's K/V through the table. The selector only
  changes the CACHE LAYOUT the serving engine allocates; the
  attention math of a gathered view is 'dense' (attn_math_impl).
  Kill switch: PADDLE_TPU_DECODE_ATTN_IMPL=dense.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

__all__ = ["write_kv", "cached_attention", "decode_attn_impl",
           "gather_pages", "write_kv_paged", "attn_math_impl",
           "cache_pspecs", "attended_tokens", "kv_view_extent"]


def cache_pspecs(paged: bool, tp_axis: str = "tp"):
    """PartitionSpecs for the decode-cache leaves on a tensor-parallel
    serving mesh (inference/serving.py `mesh=`). Both layouts are
    rank-5 with the KV-head axis at position 3 — dense
    [L, N, max_len, KV, hd] and paged [L, P, page_size, KV, hd] — so
    ONE spec head-shards either: every device holds every slot's (or
    page's) full position range for ITS heads, which keeps write_kv /
    write_kv_paged's scatters and gather_pages' page gather local
    (no resharding inside the tick). The page table is replicated —
    it indexes pages, not heads, and every shard needs the whole map.
    When tp does not divide the KV heads (deep-GQA, e.g. 2 KV heads on
    tp=4) the engine's shape-aware degrade (parallel.mesh.sharding_for
    with shape=) drops the head axis to replicated — the
    "replicated-or-head-sharded" choice, made per leaf."""
    from jax.sharding import PartitionSpec as P
    kv = P(None, None, None, tp_axis, None)
    specs = {"k": kv, "v": kv}
    if paged:
        specs["pt"] = P()
    return specs


def decode_attn_impl() -> str:
    """Selector: env PADDLE_TPU_DECODE_ATTN_IMPL > registry winner
    ('decode_attention', current backend class) > 'dense'. The env var
    is re-read per trace like the Pallas kill switches."""
    env = os.environ.get("PADDLE_TPU_DECODE_ATTN_IMPL")
    if env:
        return env
    from . import registry
    win = registry.winner("decode_attention",
                          backend=registry.backend_class(
                              jax.default_backend()))
    return win or "dense"


def attn_math_impl(impl: str | None = None) -> str:
    """The attention-math flavor for a given selector: 'paged' is a
    cache LAYOUT — its gathered per-slot view runs the 'dense' f32
    math (bit-parity with the dense pool is the whole point)."""
    impl = impl or decode_attn_impl()
    return "dense" if impl == "paged" else impl


def gather_pages(pages, table):
    """Re-linearize per-slot cache views from the page pool.

    pages [P, page_size, KV, hd]; table [B, max_pages] int32 of
    physical page ids. -> [B, max_pages * page_size, KV, hd] where
    view index p holds the K/V written at logical position p (page
    p // page_size at offset p % page_size) — so `cached_attention`
    over the view is bit-identical to the dense [B, S, ...] cache.
    Unmapped table entries point at the reserved scratch page 0; the
    position mask keeps its garbage at an exact softmax 0."""
    B, mp = table.shape
    ps = pages.shape[1]
    v = jnp.take(pages, table.reshape(-1), axis=0)     # [B*mp, ps, KV, hd]
    return v.reshape(B, mp * ps, *pages.shape[2:])


def write_kv_paged(pages, table, k, pos):
    """Scatter the step's k (or v) [B, T, KV, hd] into the page pool
    [P, page_size, KV, hd] through the per-slot table [B, max_pages].
    Token t of row b sits at logical position pos(+t) -> physical
    (table[b, p // ps], p % ps). Rows whose table maps to the scratch
    page (freed slots, positions past a slot's allocation) write
    garbage there — never attended. The scatter is the paged analog of
    write_kv's dynamic_update_slice: XLA keeps it in-place on the
    donated pool buffer."""
    B, T = k.shape[:2]
    ps = pages.shape[1]
    qpos = _query_positions(pos, B, T)                 # [B, T]
    raw_idx = qpos // ps
    page_idx = jnp.clip(raw_idx, 0, table.shape[1] - 1)
    page_id = jnp.take_along_axis(table, page_idx, axis=1)      # [B, T]
    # positions past the table (bucket pad beyond max_len) go to the
    # scratch page, never clamp onto a real tail page
    page_id = jnp.where(raw_idx < table.shape[1], page_id, 0)
    off = qpos % ps
    upd = k.astype(pages.dtype).reshape(B * T, *k.shape[2:])
    return pages.at[page_id.reshape(-1), off.reshape(-1)].set(upd)


def write_kv(kc, k, pos):
    """Write the step's k (or v) [B, T, KV, hd] into the cache
    [B, S, KV, hd] at position(s) `pos` — scalar (one
    dynamic_update_slice; XLA aliases the donated buffer) or [B]
    per-row (each slot writes at its own offset, the serving engine's
    in-place slot write). Per-row multi-token writes (T > 1 — the
    speculative verify pass lands the current token + gamma drafts in
    one call) go through a scatter whose out-of-bounds rows DROP: a
    draft position past the cache end must vanish, not clamp onto (and
    corrupt) the row's tail the way dynamic_update_slice's
    start-index clamping would."""
    k = k.astype(kc.dtype)
    if jnp.ndim(pos) == 0:
        return jax.lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
    B, T = k.shape[:2]
    if T == 1:
        return jax.vmap(
            lambda c, u, p: jax.lax.dynamic_update_slice(c, u, (p, 0, 0))
        )(kc, k, pos)
    qpos = _query_positions(pos, B, T)                 # [B, T]
    rows = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None],
                            (B, T))
    return kc.at[rows, qpos].set(k, mode="drop")


def attended_tokens(positions, active):
    """In-jit telemetry tap: total cache tokens this tick's attention
    ADMITS (the `<= position` mask of `cached_attention`) — per active
    row, positions[b] cache slots plus the current token. This is the
    roofline-attribution observable (profiler/serving_telemetry
    `attended` field): the attention-math FLOPs and the *useful* KV
    bytes scale with it, while the implementation's KV read scales
    with the full view extent (`kv_view_extent`) — the gap between the
    two is the masked-waste column of tools/serving_attrib.py."""
    return jnp.sum(jnp.where(active, positions + 1, 0)).astype(jnp.int32)


def kv_view_extent(paged: bool, max_len: int, max_pages: int = 0,
                   page_size: int = 0) -> int:
    """Host-side: the per-row cache positions one decode-attention call
    actually READS — the dense pool attends its whole [*, max_len]
    row under the mask, and the paged gather materializes the full
    [*, max_pages * page_size] table view (unmapped entries hit the
    scratch page but their bytes still move). The cost-model's
    KV-gather phase prices against this, not against live tokens."""
    return max_pages * page_size if paged else max_len


def _query_positions(pos, B, T):
    """Absolute positions of the T queries per row -> [B, T]."""
    offs = jnp.arange(T, dtype=jnp.int32)[None, :]
    if jnp.ndim(pos) == 0:
        return jnp.broadcast_to(pos + offs, (B, T))
    return pos[:, None] + offs


def cached_attention(q, kc, vc, pos, impl: str | None = None):
    """Masked attention of q [B, T, H, hd] against the cache kc/vc
    [B, S, KV, hd]; query t of row b sits at absolute position
    `pos[b] + t` (pos scalar or [B]) and sees cache slots <= that
    position. Returns ctx [B, T, H, hd] float32 (callers cast).

    Slots above the row's own position are masked to -inf before the
    softmax, so stale cache contents (a freed slot's previous request,
    bucket-pad garbage beyond the true prompt length) contribute an
    exact 0.0 — the serving engine's correctness rests on this."""
    B, T, H, hd = q.shape
    S, KV = kc.shape[1], kc.shape[2]
    G = H // KV
    impl = attn_math_impl(impl)
    if impl not in ("dense", "mixed"):
        raise ValueError(
            f"unknown decode_attention impl {impl!r} (dense|mixed|paged)")
    dot_dt = kc.dtype if impl == "mixed" else jnp.float32
    scale = 1.0 / math.sqrt(hd)

    qf = q.reshape(B, T, KV, G, hd).astype(dot_dt) * jnp.asarray(
        scale, dot_dt)
    s = jnp.einsum("btkgd,bskd->bkgts", qf, kc.astype(dot_dt))
    qpos = _query_positions(pos, B, T)                             # B,T
    # mask [B,1,1,T,S] broadcast over the (kv-head, group) axes
    mask = (jnp.arange(S, dtype=jnp.int32)[None, :]
            <= qpos[..., None])[:, None, None, :, :]
    s = jnp.where(mask, s.astype(jnp.float32), -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bkgts,bskd->btkgd", p.astype(dot_dt)
                     if impl == "mixed" else p, vc.astype(dot_dt))
    return ctx.reshape(B, T, H, hd).astype(jnp.float32)
