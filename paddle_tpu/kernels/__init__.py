"""Pallas TPU kernels for the hot fused ops (the reference's
paddle/fluid/operators/fused/ zoo, rebuilt as TPU kernels)."""
from . import flash_attention  # noqa: F401
