"""Pallas TPU kernels for the hot fused ops (the reference's
paddle/fluid/operators/fused/ zoo, rebuilt as TPU kernels) plus the
kernel-primitive library (the reference's KPS layer,
paddle/phi/kernels/primitive/kernel_primitives.h) they are built from."""
from . import flash_attention, primitives  # noqa: F401
