"""Fused dequant-matmul for weight-only int8 serving.

Reference analog: the int8 kernel-substitution pass's matmul
(quant2_int8_mkldnn_pass.py:1 — int8 weights, fp activations, dequant
fused into the kernel epilogue), restricted to the WEIGHT-ONLY form the
serving engines use (quantization/serving.py): activations stay in the
compute dtype, weights stream from HBM as int8 with per-output-channel
fp32 scales, and the dequantization never materializes an fp copy of
the weight in HBM — that copy not existing IS the feature (weight HBM
traffic halves vs bf16, quarters vs f32, which is what a bandwidth-
bound decode tick actually pays for).

Two implementations, selected through the kernels/registry.py seam
(kernel "quant_matmul", impls off|xla|pallas):

- 'xla'    jax dot_general on the fp activations against the int8
           weight upcast IN THE FUSION (XLA keeps the convert fused
           into the dot's operand stream), per-output-channel scale
           applied to the f32 accumulator as the epilogue. The
           portable fallback — CPU tests exercise this real path.
- 'pallas' hand-tiled TPU kernel: x tiles [bm, K] and int8 w tiles
           [K, bn] stage through VMEM, the int8->f32 convert happens
           in registers inside the matmul tile (the Pallas-guide
           quantization pattern), the f32 accumulator picks up the
           scale tile in the epilogue. Interpret-mode parity vs the
           'xla' impl is EXACT (same contraction, same f32
           accumulation order — tests/test_quant_serving.py pins it).

Both impls compute (x @ w_q) * scale with an f32 accumulator and cast
back to x.dtype. The per-output-channel scale commutes with the
contraction, so this equals the dequant-first oracle
x @ (w_q.astype(f32) * scale) up to one fp rounding per product —
the parity tests hold the impls bitwise-identical to EACH OTHER and
allclose to the dequant-first oracle.

Selection and the kill switch (the spec_decode pattern — env beats
everything, unrecognized values fail SAFE to off):

- env PADDLE_TPU_QUANT: 'off'/'0'/'false'/'no'/'fp'/'dense' disable
  weight-only quant even for engines built with quant="int8";
  'xla'/'pallas' enable it AND pin the matmul impl; '1'/'on'/'true'/
  'yes'/'int8' enable it with the portable 'xla' impl; anything else
  warns on stderr and counts as OFF (a typo must kill, not enable).
- registry: winner("quant_matmul") — written only by the evidence-
  gated sweep (tools/bench_serving.py --quant --adopt, which refuses
  adoption unless weight bytes <= 0.55x fp AND tokens/s >= 0.95x fp).
- default: off.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .primitives import pad_to as _pad_to, round_up as _round_up

__all__ = ["ENV_QUANT", "quant_impl", "resolve_quant", "matmul_impl",
           "quant_matmul", "leaf_matmul"]

ENV_QUANT = "PADDLE_TPU_QUANT"

_OFF_VALUES = frozenset({"0", "off", "false", "no", "fp", "dense"})
_ON_VALUES = frozenset({"1", "on", "true", "yes", "int8"})
_IMPL_VALUES = frozenset({"xla", "pallas"})


def _env_value() -> str:
    """Read + classify PADDLE_TPU_QUANT: '' (unset), 'off', 'xla' or
    'pallas'. Unrecognized values are OFF with a stderr warning — this
    env var is the kill switch, and a typo that silently enabled
    quantized serving would do the exact opposite of what the operator
    reached for (the spec_decode fail-safe rule)."""
    env = os.environ.get(ENV_QUANT, "").strip().lower()
    if not env:
        return ""
    if env in _IMPL_VALUES:
        return env
    if env in _ON_VALUES:
        return "xla"
    if env not in _OFF_VALUES:
        import sys
        print(f"[quant_matmul] {ENV_QUANT}={env!r} is not one of "
              f"{sorted(_IMPL_VALUES | _ON_VALUES)} / "
              f"{sorted(_OFF_VALUES)}; treating as 'off' (the kill "
              "switch fails safe)", file=sys.stderr, flush=True)
    return "off"


def quant_impl() -> str:
    """Selector: env PADDLE_TPU_QUANT > registry winner
    ('quant_matmul', current backend class) > 'off'. Re-read per
    engine build like the other kill switches."""
    env = _env_value()
    if env:
        return env
    from . import registry
    win = registry.winner("quant_matmul",
                          backend=registry.backend_class(
                              jax.default_backend()))
    return win or "off"


def resolve_quant(knob: str) -> bool:
    """Engine-build resolution of the quant knob ('auto' | 'off' |
    'int8') against the selector. The env kill switch is absolute: an
    off value disables quantization even for knob='int8' (same
    asymmetry as PADDLE_TPU_SPEC_DECODE — docs/serving.md)."""
    if _env_value() == "off":
        return False
    if knob == "off":
        return False
    if knob == "int8":
        return True
    if knob == "auto":
        return quant_impl() != "off"
    raise ValueError(f"quant {knob!r} (auto|off|int8)")


def matmul_impl() -> str:
    """Which implementation a quant_matmul SITE runs: 'pallas' when
    selected AND the backend is TPU-class (the compiled kernel targets
    Mosaic; off-TPU callers get the numerically-identical 'xla' form —
    interpret-mode coverage lives in the parity tests) AND the global
    PADDLE_TPU_DISABLE_PALLAS escape hatch is not set (the CLAUDE.md
    kill-switch convention every Pallas kernel honors), else 'xla'.
    'off' here still resolves to 'xla': an engine that already
    quantized its weights at build must keep serving them — the kill
    switch stops NEW engines from quantizing (resolve_quant), it
    cannot un-quantize a live tree."""
    sel = quant_impl()
    if (sel == "pallas"
            and jax.default_backend() in ("tpu", "axon")
            and os.environ.get("PADDLE_TPU_DISABLE_PALLAS", "")
            not in ("1", "true", "True")):
        return "pallas"
    return "xla"


# ------------------------------------------------------------ xla impl
def _xla_quant_matmul(x2d, w_q, scale):
    """(x @ w_q) * scale with an f32 accumulator: the int8 weight
    upcasts inside the dot's fusion (no fp weight copy in HBM), the
    per-output-channel scale lands on the accumulator."""
    y = jax.lax.dot_general(
        x2d.astype(jnp.float32), w_q.astype(jnp.float32),
        (((1,), (0,)), ((), ())))
    return y * scale.astype(jnp.float32)


# --------------------------------------------------------- pallas impl
def _qmm_kernel(x_ref, w_ref, s_ref, o_ref):
    """One [bm, bn] output tile: the int8 weight tile converts to f32
    IN REGISTERS (never touching HBM as fp), the full-K dot accumulates
    in f32, and the scale tile is the epilogue."""
    acc = jnp.dot(x_ref[...].astype(jnp.float32),
                  w_ref[...].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...]


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "interpret"))
def _pallas_quant_matmul(x2d, w_q, scale, block_m=128, block_n=128,
                         interpret=False):
    from jax.experimental import pallas as pl

    M, K = x2d.shape
    N = w_q.shape[1]
    # K is x's lane axis (128-mult) AND the int8 w's sublane axis
    # (32-mult) — pad to 128 covers both; zero-padding contributes an
    # exact 0.0 to every accumulator, so parity with the xla impl holds
    bm = min(block_m, _round_up(M, 16))
    bn = min(block_n, _round_up(N, 128))
    x = _pad_to(_pad_to(x2d, 0, bm), 1, 128)
    w = _pad_to(_pad_to(w_q, 0, 128), 1, bn)
    s = _pad_to(scale.astype(jnp.float32), 0, bn).reshape(1, -1)
    grid = (x.shape[0] // bm, w.shape[1] // bn)

    y = pl.pallas_call(
        _qmm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, x.shape[1]), lambda i, j: (i, 0)),
            pl.BlockSpec((w.shape[0], bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], w.shape[1]),
                                       jnp.float32),
        interpret=interpret,
    )(x, w, s)
    return y[:M, :N]


# --------------------------------------------------------- public entry
def quant_matmul(x, w_q, scale, impl: str | None = None,
                 interpret: bool = False):
    """y = x @ dequant(w_q): x [..., K] float, w_q [K, N] int8, scale
    [N] f32 per-output-channel. Returns [..., N] in x.dtype. `impl`
    overrides the selector (tests); `interpret` runs the Pallas kernel
    in interpreter mode (CPU parity tests)."""
    impl = impl or matmul_impl()
    if impl not in _IMPL_VALUES:
        raise ValueError(f"unknown quant_matmul impl {impl!r} "
                         "(xla|pallas)")
    lead = x.shape[:-1]
    x2d = x.reshape(-1, x.shape[-1])
    if impl == "pallas":
        y = _pallas_quant_matmul(x2d, w_q, scale, interpret=interpret)
    else:
        y = _xla_quant_matmul(x2d, w_q, scale)
    return y.reshape(*lead, w_q.shape[1]).astype(x.dtype)


def leaf_matmul(x, leaves, name: str):
    """x [B, T, K] @ leaf `name` [K, N]: the fp einsum when the tree
    holds the fp weight, the fused dequant-matmul when it holds the
    int8 serving pair (`<name>_q` + `<name>_scale` —
    quantization/serving.quantize_serving_params). THE seam the cached
    forwards route every block matmul through (models/gpt.py,
    models/llama.py), so dense/paged/spec-draft/tp paths all pick the
    quantized weights up from the params tree itself."""
    w_q = leaves.get(name + "_q")
    if w_q is not None:
        return quant_matmul(x, w_q, leaves[name + "_scale"])
    return jnp.einsum("btk,kn->btn", x, leaves[name].astype(x.dtype))
