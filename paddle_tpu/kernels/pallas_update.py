"""Hand-tiled Pallas fused AdamW/AMP master update.

Reference analog: the fused Adam(W) multi-tensor kernel
(paddle/phi/kernels/gpu/adamw_kernel.cu — one pass reading p/g/m/v and
writing p'/m'/v' with f32 master math over low-precision params).

TPU-native design: the optimizer update is pure elementwise traffic —
7 HBM streams (p, g, m, v in; p', m', v' out) and ~10 flops/element —
so the only thing that matters is touching each byte exactly once. XLA
usually fuses the jax-level update well, but splits it around dtype
casts and the per-leaf loop; this kernel is ONE launch per leaf with
the f32 master math (m/v kept f32, the param read in its storage dtype,
updated in f32, written back in storage dtype — the AMP master-weight
pattern without materializing a separate master copy) and its numerics
are rule-for-rule the models.gpt.apply_adamw oracle.

Wired behind gpt.apply_adamw when the registry names 'pallas' for the
'fused_update' kernel on a TPU-class backend (evidence-gated adoption —
kernels/registry.py); PADDLE_TPU_DISABLE_PALLAS (global) and
PADDLE_TPU_DISABLE_PALLAS_UPDATE (targeted) kill it. The jax-level form
stays the default and the parity oracle (tests/test_kernels.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .primitives import pad_to as _pad_dim

_LANES = 128      # elementwise: everything reshapes to [rows, 128]
_BLOCK_R = 256    # rows per grid step (256*128 f32 = 128 KiB/operand)


def _update_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                   po_ref, mo_ref, vo_ref):
    """One (BLOCK_R, 128) tile of the AdamW update. `s_ref` carries the
    step hyperparameters broadcast down lane 0: [lr, b1, b2, eps, wd,
    bc1, bc2] — traced values (bc1/bc2 depend on the step counter), so
    they ride as a tiny input block rather than compile-time
    constants."""
    lr = s_ref[0, 0]
    b1 = s_ref[0, 1]
    b2 = s_ref[0, 2]
    eps = s_ref[0, 3]
    wd = s_ref[0, 4]
    bc1 = s_ref[0, 5]
    bc2 = s_ref[0, 6]
    gf = g_ref[...].astype(jnp.float32)
    m_new = b1 * m_ref[...] + (1 - b1) * gf
    v_new = b2 * v_ref[...] + (1 - b2) * jnp.square(gf)
    den = jnp.sqrt(v_new / bc2) + eps
    p_new = p_ref[...].astype(jnp.float32) * (1.0 - lr * wd) - \
        lr * (m_new / bc1) / den
    po_ref[...] = p_new.astype(po_ref.dtype)
    mo_ref[...] = m_new
    vo_ref[...] = v_new


def _to_tiles(a, dtype=None):
    """Flatten to [rows, 128] padded to the row block (zeros: the pad
    lanes update harmlessly — den >= eps > 0 — and are sliced away)."""
    flat = a.reshape(-1)
    if dtype is not None:
        flat = flat.astype(dtype)
    flat = _pad_dim(flat, 0, _LANES)
    rows = flat.reshape(-1, _LANES)
    return _pad_dim(rows, 0, _BLOCK_R)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _leaf_update(p, g, m, v, scal, interpret=False):
    """AdamW-update ONE leaf: returns (p', m', v') with p' in p.dtype
    and the moments in f32. `scal` is the packed [7] f32 hyperparameter
    vector (see _update_kernel)."""
    shape, n = p.shape, p.size
    pt = _to_tiles(p)
    gt = _to_tiles(g)
    mt = _to_tiles(m, jnp.float32)
    vt = _to_tiles(v, jnp.float32)
    srow = jnp.zeros((1, _LANES), jnp.float32).at[0, :7].set(
        scal.astype(jnp.float32))
    grid = (pt.shape[0] // _BLOCK_R,)
    row_spec = pl.BlockSpec((_BLOCK_R, _LANES), lambda i: (i, 0))
    p2, m2, v2 = pl.pallas_call(
        _update_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, _LANES), lambda i: (0, 0)),
                  row_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(pt.shape, p.dtype),
                   jax.ShapeDtypeStruct(pt.shape, jnp.float32),
                   jax.ShapeDtypeStruct(pt.shape, jnp.float32)],
        interpret=interpret,
    )(srow, pt, gt, mt, vt)
    unpad = lambda t: t.reshape(-1)[:n].reshape(shape)
    return unpad(p2), unpad(m2), unpad(v2)


def fused_apply_adamw(grads, params, opt_state, lr, beta1=0.9,
                      beta2=0.95, eps=1e-8, weight_decay=0.1,
                      interpret=False):
    """Drop-in for models.gpt.apply_adamw running every leaf through the
    Pallas kernel — same tree plumbing, same contract, same math."""
    step = opt_state["step"] + 1.0
    bc1 = 1.0 - beta1 ** step
    bc2 = 1.0 - beta2 ** step
    scal = jnp.stack([jnp.asarray(x, jnp.float32) for x in
                      (lr, beta1, beta2, eps, weight_decay, bc1, bc2)])

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(opt_state["m"])
    flat_v = treedef.flatten_up_to(opt_state["v"])
    new = [_leaf_update(p, g, m, v, scal, interpret=interpret)
           for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_params = jax.tree_util.tree_unflatten(treedef,
                                              [n[0] for n in new])
    new_m = jax.tree_util.tree_unflatten(treedef, [n[1] for n in new])
    new_v = jax.tree_util.tree_unflatten(treedef, [n[2] for n in new])
    return new_params, {"m": new_m, "v": new_v, "step": step}


def fused_update_enabled() -> bool:
    """The gpt.apply_adamw consult: TPU-class backend, Pallas alive
    (global + targeted kill switches), and the registry's evidence-gated
    'fused_update' winner naming 'pallas'. No entry = jax default."""
    import os
    from .flash_attention import _pallas_enabled
    if not _pallas_enabled():
        return False
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_UPDATE", "") in (
            "1", "true", "True"):
        return False
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    from . import registry
    return registry.winner("fused_update", backend="tpu") == "pallas"
