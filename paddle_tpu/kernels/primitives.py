"""Kernel primitives: the reusable block-level pieces TPU Pallas kernels
are assembled from.

Reference analog: the KPS layer (Kernel Primitive API) at
paddle/phi/kernels/primitive/kernel_primitives.h — portable block-level
compute primitives (ElementwiseUnary/Binary, Reduce) and data movers
(ReadData/WriteData with boundary handling) that the reference's CUDA
kernels are written against, so kernel bodies express algorithms, not
addressing. The TPU translation: Pallas refs already own data movement,
so the primitives here are the recurring *algorithmic* building blocks —
grid/tile arithmetic, boundary + causal masks over block-local iota, the
online-softmax/log-sum-exp update, per-row scalar storage conventions —
shared by the production kernels (pallas_attention, pallas_ce) and
importable by custom-op authors as paddle_tpu.kernels.primitives.

Everything is a pure jax function usable BOTH inside a Pallas kernel
body (on values read from refs) and in jax-level blockwise fallbacks.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# Large-negative float used for masked scores: exp(_NEG_INF - m) == 0 in
# f32 without the NaN hazards of -inf arithmetic inside kernels.
NEG_INF = -1e30

# Per-row scalars (lse, loss, running max) are stored this many lanes
# wide: the minimum f32 VMEM tile is (8, 128) sublanes x lanes, so lane
# widths below 128 don't shrink VMEM, but HBM traffic/storage for the
# materialized output shrinks 16x vs broadcasting to a full 128 lanes.
ROW_SCALAR_LANES = 8


# ------------------------------------------------------------ tile math
def cdiv(a: int, b: int) -> int:
    """Ceil division for grid sizing."""
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    """Round `a` up to a multiple of `b`."""
    return cdiv(a, b) * b


def pad_to(x, axis: int, mult: int, value=0):
    """Pad `axis` up to a multiple of `mult` (the KPS ReadData boundary
    analog: kernels then run on full tiles and slice the tail off after
    the pallas_call instead of branching per element)."""
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def env_block(name: str, default: int) -> int:
    """Block-size override hook (PADDLE_TPU_FLASH_BLOCK_*, ...) so the
    offline sweeps can tune without code edits. Must be resolved OUTSIDE
    the jitted kernels: the jit cache keys on the resolved ints, so
    reading env inside a trace would freeze the first-seen value."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# ------------------------------------------------------- block positions
def tile_positions(block_idx, block_size: int, shape, dim: int):
    """Global positions of one tile's elements along `dim`: an int32
    tensor of `shape` whose entries are block_idx*block_size + local
    offset. The building block for every boundary/causal/gather mask."""
    return block_idx * block_size + jax.lax.broadcasted_iota(
        jnp.int32, shape, dim)


def bounds_mask(positions, limit):
    """True where a global position is in-range (KPS boundary handling:
    applied to scores/probabilities instead of predicating loads)."""
    return positions < limit


def causal_mask(q_positions, k_positions):
    """True where attention is allowed (query position >= key position)."""
    return q_positions >= k_positions


def causal_block_live(i, j, block_q: int, block_k: int):
    """Whether kv block j overlaps the causal region of q block i at all
    — the grid-level skip that removes the upper-triangular half of the
    flash-attention work."""
    return j * block_k <= i * block_q + block_q - 1


# --------------------------------------------------------- online softmax
def online_softmax_update(m_prev, l_prev, s):
    """One streaming-softmax state update over a new score tile `s`
    ([rows, block] f32; masked entries at NEG_INF).

    Returns (m_new, l_new, p, corr):
      m_new  [rows,1] running max
      l_new  [rows,1] running normalizer (corrected + this tile's sum)
      p      [rows,block] this tile's unnormalized probabilities
      corr   [rows,1] factor that rescales any accumulator built under
             m_prev (acc = acc*corr + p @ v is the flash-attention use;
             cross-entropy has no accumulator and ignores it).
    """
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, l_new, p, corr


def logsumexp_finalize(m, l):
    """Final log-normalizer from streamed (m, l) state; the 1e-30 floor
    keeps fully-masked rows finite (they produce lse = m - 69)."""
    return m + jnp.log(jnp.maximum(l, 1e-30))


def softmax_finalize(acc, l):
    """Normalize a p@v-style accumulator by the streamed l."""
    return acc / jnp.maximum(l, 1e-30)
