"""Hand-tiled Pallas softmax-cross-entropy over a large vocab.

Reference analog: the fused softmax_with_cross_entropy kernel
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu) — per-row loss without
materializing the probability tensor.

TPU-native design: tokens tile the grid's outer axis, vocab tiles the
inner axis with the online-logsumexp state (m, l) and the gathered
target logit living in VMEM scratch across vocab tiles — HBM reads the
bf16 logits exactly ONCE and never writes an f32 [T, V] intermediate
(the jax-level fused CE upcasts the whole logits tensor to f32 first).
Backward is one pass: d_logits tile = (softmax − onehot) · g, rebuilt
from the saved per-row logsumexp.

Wired via jax.custom_vjp behind losses.fused_softmax_ce when the
backend is TPU-class and shapes tile; the jax-level form remains the
fallback and the numerics oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .primitives import (NEG_INF as _NEG_INF, ROW_SCALAR_LANES as _LANES,
                         bounds_mask, logsumexp_finalize,
                         online_softmax_update, pad_to as _pad_dim,
                         tile_positions)


def _fwd_kernel(x_ref, tgt_ref, loss_ref, lse_ref, m_ref, l_ref, t_ref,
                *, block_t, block_v, n_valid_v):
    j = pl.program_id(1)                   # vocab tile (innermost)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        t_ref[...] = jnp.zeros_like(t_ref)

    s = x_ref[...].astype(jnp.float32)                    # (BT, BV)
    vpos = tile_positions(j, block_v, (block_t, block_v), 1)
    s = jnp.where(bounds_mask(vpos, n_valid_v), s, _NEG_INF)  # pad tiles

    m_new, l_new, _p, _corr = online_softmax_update(
        m_ref[:, :1], l_ref[:, :1], s)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    # target logit: exactly one tile holds it per row
    tgt = tgt_ref[:, :1]                                   # (BT, 1) int32
    hit = (vpos == tgt)
    t_ref[...] += jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        t_ref.shape)

    @pl.when(j == nv - 1)
    def _finalize():
        lse = logsumexp_finalize(m_ref[:, :1], l_ref[:, :1])
        loss_ref[...] = jnp.broadcast_to(lse - t_ref[:, :1],
                                         loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_kernel(x_ref, tgt_ref, lse_ref, g_ref, dx_ref,
                *, block_t, block_v, n_valid_v):
    j = pl.program_id(1)
    s = x_ref[...].astype(jnp.float32)
    vpos = tile_positions(j, block_v, (block_t, block_v), 1)
    p = jnp.exp(s - lse_ref[:, :1])
    p = jnp.where(bounds_mask(vpos, n_valid_v), p, 0.0)
    onehot = (vpos == tgt_ref[:, :1]).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g_ref[:, :1]).astype(dx_ref.dtype)


def _fused_kernel(x_ref, tgt_ref, loss_ref, dx_ref, m_ref, l_ref, t_ref,
                  lse_ref, *, block_t, block_v, n_valid_v):
    """One-pass CE+grad: grid (tokens, PHASE, vocab). Phase 0 is the
    online-logsumexp sweep (exactly _fwd_kernel), finalizing the row lse
    into VMEM scratch; phase 1 re-streams the same vocab tiles and emits
    d_logits = softmax − onehot directly — the training-path backward
    (_bwd_kernel) collapses into this launch, so the VJP never re-reads
    the logits or saves the lse residual. The dx BlockSpec maps phase 0
    onto column block 0: that window is rewritten by phase 1's j=0 step
    before any flush, so no garbage reaches HBM."""
    ph = pl.program_id(1)
    j = pl.program_id(2)
    nv = pl.num_programs(2)

    @pl.when((ph == 0) & (j == 0))
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        t_ref[...] = jnp.zeros_like(t_ref)

    s = x_ref[...].astype(jnp.float32)                    # (BT, BV)
    vpos = tile_positions(j, block_v, (block_t, block_v), 1)
    inb = bounds_mask(vpos, n_valid_v)
    tgt = tgt_ref[:, :1]                                  # (BT, 1) int32

    @pl.when(ph == 0)
    def _accumulate():
        sm = jnp.where(inb, s, _NEG_INF)                  # pad tiles
        m_new, l_new, _p, _corr = online_softmax_update(
            m_ref[:, :1], l_ref[:, :1], sm)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        hit = (vpos == tgt)
        t_ref[...] += jnp.broadcast_to(
            jnp.sum(jnp.where(hit, sm, 0.0), axis=-1, keepdims=True),
            t_ref.shape)

        @pl.when(j == nv - 1)
        def _finalize():
            lse = logsumexp_finalize(m_ref[:, :1], l_ref[:, :1])
            loss_ref[...] = jnp.broadcast_to(lse - t_ref[:, :1],
                                             loss_ref.shape)
            lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)

    @pl.when(ph == 1)
    def _grad():
        p = jnp.exp(s - lse_ref[:, :1])
        p = jnp.where(inb, p, 0.0)
        onehot = (vpos == tgt).astype(jnp.float32)
        dx_ref[...] = (p - onehot).astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_fused(logits2d, targets, block_t=128, block_v=512,
              interpret=False):
    """loss [T] f32 AND unit-cotangent d_logits [T, V] in one launch."""
    T, V = logits2d.shape
    x = _pad_dim(_pad_dim(logits2d, 0, block_t), 1, block_v)
    tg = _pad_dim(targets.astype(jnp.int32), 0, block_t, value=-1)
    tg = jnp.broadcast_to(tg[:, None], (x.shape[0], _LANES))
    grid = (x.shape[0] // block_t, 2, x.shape[1] // block_v)

    loss, dx = pl.pallas_call(
        functools.partial(_fused_kernel, block_t=block_t,
                          block_v=block_v, n_valid_v=V),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda i, p, j: (i, j)),
            pl.BlockSpec((block_t, _LANES), lambda i, p, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, _LANES), lambda i, p, j: (i, 0)),
            # phase 0 parks the window on column block 0; phase 1
            # rewrites it at j=0 before the first flush
            pl.BlockSpec((block_t, block_v), lambda i, p, j: (i, p * j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((x.shape[0], _LANES), jnp.float32),
            jax.ShapeDtypeStruct(x.shape, logits2d.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_t, 128), jnp.float32),
                        pltpu.VMEM((block_t, 128), jnp.float32),
                        pltpu.VMEM((block_t, 128), jnp.float32),
                        pltpu.VMEM((block_t, 128), jnp.float32)],
        interpret=interpret,
    )(x, tg)
    return loss[:T, 0], dx[:T, :V]


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_fwd(logits2d, targets, block_t=128, block_v=512, interpret=False):
    T, V = logits2d.shape
    x = _pad_dim(_pad_dim(logits2d, 0, block_t), 1, block_v)
    tg = _pad_dim(targets.astype(jnp.int32), 0, block_t, value=0)
    tg = jnp.broadcast_to(tg[:, None], (x.shape[0], _LANES))
    grid = (x.shape[0] // block_t, x.shape[1] // block_v)

    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_t=block_t, block_v=block_v,
                          n_valid_v=V),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((x.shape[0], _LANES), jnp.float32),
            jax.ShapeDtypeStruct((x.shape[0], _LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_t, 128), jnp.float32),
                        pltpu.VMEM((block_t, 128), jnp.float32),
                        pltpu.VMEM((block_t, 128), jnp.float32)],
        interpret=interpret,
    )(x, tg)
    return loss[:T, 0], lse[:T, 0]


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_bwd(logits2d, targets, lse, g, block_t=128, block_v=512,
            interpret=False):
    T, V = logits2d.shape
    x = _pad_dim(_pad_dim(logits2d, 0, block_t), 1, block_v)
    tg = _pad_dim(targets.astype(jnp.int32), 0, block_t, value=-1)
    tg = jnp.broadcast_to(tg[:, None], (x.shape[0], _LANES))
    # padded rows: lse=+inf makes p=0 so their dx is 0
    lse2 = _pad_dim(lse, 0, block_t, value=3.4e38)
    lse2 = jnp.broadcast_to(lse2[:, None], (x.shape[0], _LANES))
    g2 = jnp.broadcast_to(_pad_dim(g, 0, block_t)[:, None],
                          (x.shape[0], _LANES))
    grid = (x.shape[0] // block_t, x.shape[1] // block_v)

    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, block_t=block_t, block_v=block_v,
                          n_valid_v=V),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(x.shape, logits2d.dtype),
        interpret=interpret,
    )(x, tg, lse2, g2)
    return dx[:T, :V]


# ------------------------------------------------------------- public entry
def _tuned_ce_blocks(logits2d):
    """(block_t, block_v) from the persistent autotune cache (populated by
    tools/autotune_kernels.py; key matches its `ce::T{T}_V{V}_{dtype}`),
    else the shipped 128/512 defaults."""
    from .autotune import cached
    sig = f"T{logits2d.shape[0]}_V{logits2d.shape[1]}_{logits2d.dtype}"
    return cached("ce", sig) or (128, 512)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def ce_with_logits(logits2d, targets, interpret=False):
    """Per-row cross entropy: [T, V] float, [T] int → [T] f32 loss."""
    bt, bv = _tuned_ce_blocks(logits2d)
    loss, _ = _ce_fwd(logits2d, targets, block_t=bt, block_v=bv,
                      interpret=interpret)
    return loss


def _ce_vjp_fwd(logits2d, targets, interpret=False):
    bt, bv = _tuned_ce_blocks(logits2d)
    loss, lse = _ce_fwd(logits2d, targets, block_t=bt, block_v=bv,
                        interpret=interpret)
    return loss, (logits2d, targets, lse)


def _ce_vjp_bwd(interpret, res, g):
    logits2d, targets, lse = res
    bt, bv = _tuned_ce_blocks(logits2d)
    dx = _ce_bwd(logits2d, targets, lse, g.astype(jnp.float32),
                 block_t=bt, block_v=bv, interpret=interpret)
    return dx, None


ce_with_logits.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def ce_fused_train(logits2d, targets, interpret=False):
    """The training-path flavor: per-row loss whose VJP costs ~nothing —
    the ONE-PASS fused kernel (_ce_fused) already emitted d_logits with
    the loss, so backward is a cotangent scale instead of a second
    kernel re-reading the logits. Select it only where the grad is
    always taken (registry impl 'pallas_fused'): a primal-only call
    computes and discards the d_logits half."""
    bt, bv = _tuned_ce_blocks(logits2d)
    loss, _ = _ce_fused(logits2d, targets, block_t=bt, block_v=bv,
                        interpret=interpret)
    return loss


def _ce_fused_vjp_fwd(logits2d, targets, interpret=False):
    bt, bv = _tuned_ce_blocks(logits2d)
    loss, dx = _ce_fused(logits2d, targets, block_t=bt, block_v=bv,
                         interpret=interpret)
    return loss, (dx,)


def _ce_fused_vjp_bwd(interpret, res, g):
    (dx,) = res
    out = (dx.astype(jnp.float32)
           * g.astype(jnp.float32)[:, None]).astype(dx.dtype)
    return out, None


ce_fused_train.defvjp(_ce_fused_vjp_fwd, _ce_fused_vjp_bwd)


def suitable(logits_shape) -> bool:
    """The kernel pays off when the vocab axis is large; tiny vocabs stay
    on the jax path (padding waste dominates below one tile)."""
    return logits_shape[-1] >= 512
