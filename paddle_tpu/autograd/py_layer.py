"""PyLayer — user-defined autograd functions.

Reference analog: python/paddle/autograd/py_layer.py:248 +
/root/reference/paddle/fluid/eager/pylayer/. Here a PyLayer inserts a custom
TapeNode whose vjp calls the user's `backward` (which itself runs paddle_tpu
ops, so it stays jax-traceable and can appear inside a jit region).
"""
from __future__ import annotations

from typing import Any, List

import numpy as np

from ..framework import dtype as dtypes
from ..framework.autograd import TapeNode, is_grad_enabled, no_grad
from ..framework.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = [t.detach() if isinstance(t, Tensor) else t
                       for t in tensors]

    def saved_tensor(self):
        return list(self._saved)


class _PyLayerNode(TapeNode):
    __slots__ = ("ctx", "backward_fn", "n_inputs")

    def __init__(self, ctx, backward_fn, inputs, out_avals, diff_in_mask,
                 diff_out_mask):
        super().__init__(
            name="pylayer", closure=lambda *a: None, saved_vals=(),
            inputs=inputs, diff_in_mask=diff_in_mask,
            diff_out_mask=diff_out_mask, out_avals=out_avals)
        self.ctx = ctx
        self.backward_fn = backward_fn

    def release(self):
        self.ctx = None
        self.inputs = None
        self.released = True

    def vjp(self, out_grads):
        if self.released:
            raise RuntimeError("PyLayer node released; use retain_graph=True")
        import jax.numpy as jnp
        grads_in = []
        for (shape, dt), g, m in zip(self.out_avals, out_grads,
                                     self.diff_out_mask):
            if g is None and self.ctx.materialize_grads and m:
                g = jnp.zeros(shape, dt)
            grads_in.append(Tensor(g, stop_gradient=True)
                            if g is not None else None)
        with no_grad():
            result = self.backward_fn(self.ctx, *grads_in)
        if not isinstance(result, (tuple, list)):
            result = (result,)
        out = []
        ri = iter(result)
        for m in self.diff_in_mask:
            if m:
                r = next(ri, None)
                out.append(None if r is None else
                           (r._value if isinstance(r, Tensor) else r))
            else:
                out.append(None)
        return out


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        outs = (outputs,) if single else tuple(outputs)

        grad_needed = is_grad_enabled() and any(
            not t.stop_gradient and dtypes.is_differentiable(t.dtype)
            for t in tensor_inputs)
        if grad_needed:
            diff_in = [not t.stop_gradient and
                       dtypes.is_differentiable(t.dtype)
                       for t in tensor_inputs]
            diff_out = [isinstance(o, Tensor) and
                        dtypes.is_differentiable(o.dtype) for o in outs]
            node = _PyLayerNode(
                ctx, cls.backward, tensor_inputs,
                [(tuple(o.shape), o.dtype) for o in outs],
                diff_in, diff_out)
            for i, o in enumerate(outs):
                if diff_out[i]:
                    o.stop_gradient = False
                    o._node = node
                    o._out_idx = i
        return outs[0] if single else list(outs)


class LegacyPyLayer(PyLayer):
    pass
