"""Functional autograd: jacobian/hessian/vjp/jvp.

Reference analog: python/paddle/autograd/autograd.py:30,183 and
incubate/autograd/functional.py:22,80. Because paddle_tpu's eager ops run on
jax values, these are direct applications of jax's transforms to a
functionalized view of the user's Tensor-level function — no custom
double-backward machinery needed.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax

from ..framework.tensor import Tensor
from ..framework.autograd import no_grad


def _functionalize(func: Callable):
    """Lift a Tensor->Tensor function to a jax-value function."""
    def pure(*vals):
        tensors = [Tensor(v, stop_gradient=False) for v in vals]
        with no_grad():
            out = func(*tensors)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out
    return pure


def _vals(xs):
    if isinstance(xs, (tuple, list)):
        return tuple(x._value if isinstance(x, Tensor) else x for x in xs)
    return (xs._value if isinstance(xs, Tensor) else xs,)


def _wrap(tree):
    return jax.tree_util.tree_map(lambda v: Tensor(v, stop_gradient=True),
                                  tree)


def jacobian(func, xs, is_batched=False):
    pure = _functionalize(func)
    vals = _vals(xs)
    jac = jax.jacrev(pure, argnums=tuple(range(len(vals))))(*vals)
    out = _wrap(jac)
    if not isinstance(xs, (tuple, list)):
        if isinstance(out, (tuple, list)) and len(out) == 1:
            return out[0]
    return out


def hessian(func, xs, is_batched=False):
    pure = _functionalize(func)
    vals = _vals(xs)
    hess = jax.hessian(pure, argnums=tuple(range(len(vals))))(*vals)
    out = _wrap(hess)
    if not isinstance(xs, (tuple, list)):
        while isinstance(out, (tuple, list)) and len(out) == 1:
            out = out[0]
    return out


def vjp(func, xs, v=None):
    pure = _functionalize(func)
    vals = _vals(xs)
    primals, vjp_fn = jax.vjp(pure, *vals)
    if v is None:
        import jax.numpy as jnp
        v = jax.tree_util.tree_map(lambda p: jnp.ones_like(p), primals)
    else:
        v = jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, v)
    grads = vjp_fn(v)
    outs = _wrap(primals)
    gouts = _wrap(grads)
    if not isinstance(xs, (tuple, list)) and isinstance(gouts, tuple) and len(gouts) == 1:
        gouts = gouts[0]
    return outs, gouts


def jvp(func, xs, v=None):
    pure = _functionalize(func)
    vals = _vals(xs)
    if v is None:
        import jax.numpy as jnp
        v = tuple(jnp.ones_like(x) for x in vals)
    else:
        vv = _vals(v) if isinstance(v, (tuple, list)) else _vals([v])
        v = vv
    primals, tangents = jax.jvp(pure, vals, v)
    return _wrap(primals), _wrap(tangents)
