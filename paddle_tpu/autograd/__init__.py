"""paddle_tpu.autograd namespace.

Reference analog: python/paddle/autograd/ (backward, PyLayer, jacobian).
"""
from __future__ import annotations

from ..framework.autograd import (no_grad, enable_grad, is_grad_enabled,
                                  set_grad_enabled, run_backward)
from ..framework.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Multi-tensor backward (reference: autograd/backward_mode.py:23)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), list(grad_tensors), retain_graph=retain_graph)


from .py_layer import PyLayer, PyLayerContext  # noqa: E402
from .functional import jacobian, hessian, vjp, jvp  # noqa: E402


class saved_tensors_hooks:
    """reference python/paddle/autograd/saved_tensors_hooks.py:20 — a
    context registering (pack_hook, unpack_hook) over every tensor the
    tape snapshots for backward (e.g. offload-to-host-numpy packing).
    Hooks apply to nodes RECORDED inside the context; backward may run
    after exit."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from ..framework.autograd import set_saved_tensors_hooks
        set_saved_tensors_hooks((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from ..framework.autograd import set_saved_tensors_hooks
        set_saved_tensors_hooks(None)
        return False
