"""paddle_tpu.autograd namespace.

Reference analog: python/paddle/autograd/ (backward, PyLayer, jacobian).
"""
from __future__ import annotations

from ..framework.autograd import (no_grad, enable_grad, is_grad_enabled,
                                  set_grad_enabled, run_backward)
from ..framework.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Multi-tensor backward (reference: autograd/backward_mode.py:23)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), list(grad_tensors), retain_graph=retain_graph)


from .py_layer import PyLayer, PyLayerContext  # noqa: E402
from .functional import jacobian, hessian, vjp, jvp  # noqa: E402
