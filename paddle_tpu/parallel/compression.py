"""Gradient-compression collectives for bandwidth-starved links.

Reference analog: the fleet meta-optimizers that trade gradient fidelity
for reduction bandwidth —
python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py:1 (deep
gradient compression: momentum-corrected top-k sparsification with error
feedback), fp16_allreduce_optimizer.py (reduce in half precision),
localsgd_optimizer.py (local steps + periodic parameter averaging).

TPU-native position (docs in fleet/fleet.py): on an ICI-connected slice
these are counterproductive — the interconnect outruns the compression
math, and GSPMD already fuses/overlaps the reduction. They earn their
keep on DCN-crossing multi-slice data parallelism, where the cross-slice
link is ~10-100x slower than ICI. Accordingly they are expressed as
building blocks for the explicit shard_map path (the only place a
DCN-crossing reduction is explicit), not as silent rewrites of the
single-program GSPMD step:

- `compressed_psum`: psum with the wire dtype dropped to bf16/f16.
- `dgc_compress` / `dgc_decompress`: top-k sparsification with error
  feedback (the residual accumulates what was not sent — DGC's core
  invariant), shaped for a gather-based exchange.
- `local_sgd_sync`: periodic cross-replica parameter averaging for
  local-update training.

All are pure jax functions usable inside jit/shard_map.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["compressed_psum", "dgc_compress", "dgc_decompress",
           "dgc_psum", "local_sgd_sync"]


def compressed_psum(x, axis_name: str, wire_dtype=jnp.bfloat16):
    """All-reduce `x` with the on-wire dtype reduced to `wire_dtype`
    (reference fp16_allreduce). The accumulation error is bounded by the
    cast; the result is upcast back to x.dtype. Call inside shard_map
    over `axis_name`."""
    return jax.lax.psum(x.astype(wire_dtype), axis_name).astype(x.dtype)


def dgc_compress(grad, residual, k_frac: float = 0.01
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deep-gradient-compression sparsification of one gradient tensor.

    Adds the error-feedback residual, keeps the top ceil(k_frac*n)
    entries by magnitude, and returns (values, indices, new_residual):
    the unsent mass STAYS in the residual so no gradient signal is ever
    dropped, only delayed (the DGC invariant; reference
    dgc_optimizer.py + the dgc_op CUDA kernels). Static output shapes —
    k is a trace-time constant — so the exchange compiles on TPU."""
    if not 0.0 < k_frac <= 1.0:
        raise ValueError(f"k_frac must be in (0, 1]; got {k_frac}")
    import math
    acc = (residual + grad).ravel()
    k = max(1, math.ceil(acc.shape[0] * k_frac))
    vals, idx = jax.lax.top_k(jnp.abs(acc), k)
    sent = acc[idx]
    new_residual = acc.at[idx].set(0.0).reshape(grad.shape)
    return sent, idx, new_residual


def dgc_decompress(sent, idx, shape) -> jnp.ndarray:
    """Scatter the exchanged (values, indices) back to a dense tensor."""
    n = 1
    for s in shape:
        n *= s
    return jnp.zeros((n,), sent.dtype).at[idx].add(sent).reshape(shape)


def dgc_psum(grad, residual, axis_name: str, k_frac: float = 0.01):
    """One DGC-compressed all-reduce step inside shard_map: each member
    all-gathers only its top-k (values, indices) — wire volume ~2*k*W
    floats instead of the dense n per member — then scatter-sums
    everyone's sparse contributions locally. The residual carries the
    unsent mass to the next step."""
    sent, idx, new_residual = dgc_compress(grad, residual, k_frac)
    # the EXCHANGE is sparse (this is where the bandwidth saving lives);
    # densification happens after the collective, locally. Spelled as a
    # psum of per-member [W, k] rows rather than all_gather: identical
    # wire content, and psum's output is vma-invariant so the caller can
    # declare replicated out_specs (all_gather's isn't inferred).
    from ..utils.compat import axis_size
    w = axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    k = sent.shape[0]
    all_sent = jax.lax.psum(
        jnp.zeros((w, k), sent.dtype).at[me].set(sent), axis_name)
    all_idx = jax.lax.psum(
        jnp.zeros((w, k), jnp.int32).at[me].set(idx.astype(jnp.int32)),
        axis_name)
    total = dgc_decompress(all_sent.ravel(), all_idx.ravel(), grad.shape)
    return total, new_residual


def local_sgd_sync(params, axis_name: str):
    """Average parameters across `axis_name` (reference localsgd's
    periodic sync). Call every k-th step inside the shard_map-per-replica
    training loop; between syncs each member steps on its own shard."""
    return jax.tree_util.tree_map(
        lambda p: jax.lax.pmean(p, axis_name), params)
