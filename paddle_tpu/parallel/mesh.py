"""Device mesh management — the heart of the distributed design.

Reference analog: the 4-axis CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:54,140), which builds
cartesian NCCL groups per axis. TPU-native: ONE `jax.sharding.Mesh` with
named axes replaces the whole process-group zoo — XLA GSPMD emits the right
ICI/DCN collectives from shardings, so "creating a comm group" becomes
"naming a mesh axis".

Axis convention (SURVEY.md §7): ('dp', 'fsdp', 'pp', 'mp'); 'sp' (sequence /
context parallel) reuses 'mp' Megatron-style or its own axis for ring
attention; 'ep' (expert parallel) typically aliases 'fsdp'×'mp'.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_state = threading.local()


def _mesh_stack() -> List[Mesh]:
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def build_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {'dp': 2, 'mp': 4, ...}; -1 on one axis = infer."""
    devices = list(devices if devices is not None else jax.devices())
    shape = dict(axes)
    known = 1
    infer_key = None
    for k, v in shape.items():
        if v in (-1, None):
            if infer_key is not None:
                raise ValueError("only one mesh axis may be -1")
            infer_key = k
        else:
            known *= v
    if infer_key is not None:
        shape[infer_key] = len(devices) // known
    total = int(np.prod(list(shape.values())))
    if total != len(devices):
        if total < len(devices):
            devices = devices[:total]
        else:
            raise ValueError(
                f"mesh {shape} needs {total} devices, have {len(devices)}")
    arr = np.array(devices).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


def device_keys(devices) -> List[str]:
    """Stable per-device identity strings for a Mesh or a device list
    (`str(d)` is unique per PJRT device, e.g. 'TFRT_CPU_3'). ONE home
    for the keying used by the elastic device leases
    (parallel/elastic.py), the fault injector's lose-the-last-K
    selection, and the survivors set-difference after a loss — a mesh
    and a flat list over the same devices must key identically."""
    if isinstance(devices, Mesh):
        devices = devices.devices.flat
    return [str(d) for d in devices]


def set_global_mesh(mesh: Mesh):
    _mesh_stack().clear()
    _mesh_stack().append(mesh)


def get_mesh() -> Optional[Mesh]:
    stack = _mesh_stack()
    return stack[-1] if stack else None


class use_mesh:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _mesh_stack().append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _mesh_stack().pop()
        return False


def leaf_path_name(path) -> str:
    """Last dict/attr key on a jax tree path — the ONE name-keyed
    lookup rule shared by the facade's pinned step
    (models/facade._ShardedTrainStep) and the manual pp step's
    shard_map specs (parallel/pipeline_train.py): both resolve a leaf's
    PartitionSpec from the plan's spec table by this name, so the rule
    living in one place is what keeps pins and specs agreeing leaf for
    leaf."""
    import jax.tree_util as jtu
    for entry in reversed(path):
        if isinstance(entry, jtu.DictKey):
            return str(entry.key)
        if isinstance(entry, jtu.GetAttrKey):
            return str(entry.name)
    return ""


def _clean_spec(spec: PartitionSpec, mesh: Mesh,
                shape: Optional[Sequence[int]] = None) -> PartitionSpec:
    """Adapt `spec` to `mesh`: drop axes the mesh doesn't have (per
    entry, so a spec naming both known and unknown axes keeps the known
    ones), and — with `shape` — degrade any entry whose mesh size does
    not divide the dim to replicated. ONE home for the degrade rule,
    shared by sharding_for and constraint."""
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, (tuple, list)):
            keep = tuple(a for a in entry if a in mesh.axis_names)
            cleaned.append(keep if keep else None)
        else:
            cleaned.append(entry if entry in mesh.axis_names else None)
    if shape is not None:
        # shape-aware degrade: an axis whose mesh size does not divide
        # the dim drops to replicated instead of erroring (e.g. a GQA
        # cache with 2 KV heads on a tp=4 mesh keeps its pages
        # replicated — the serving engine's "replicated-or-head-
        # sharded" choice, made per leaf)
        for i, entry in enumerate(cleaned):
            if entry is None or i >= len(shape):
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            n = int(np.prod([mesh.shape[a] for a in axes]))
            if n == 0 or shape[i] % n != 0:
                cleaned[i] = None
    return PartitionSpec(*cleaned)


def sharding_for(spec: PartitionSpec, mesh: Optional[Mesh] = None,
                 shape: Optional[Sequence[int]] = None
                 ) -> Optional[NamedSharding]:
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    # drop axes the mesh doesn't have (lets the same model run on smaller
    # meshes — e.g. TP spec on a dp-only mesh degrades to replicated)
    return NamedSharding(mesh, _clean_spec(spec, mesh, shape))


def remap_spec_axes(spec: PartitionSpec, mapping: Dict[str, str]
                    ) -> PartitionSpec:
    """Rename mesh axes inside a PartitionSpec: entries map through
    `mapping`; axes absent from the mapping drop to None (replicated).
    Tuple entries keep only their mapped members."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            keep = tuple(mapping[a] for a in entry if a in mapping)
            out.append(keep if len(keep) > 1
                       else (keep[0] if keep else None))
        else:
            out.append(mapping.get(entry))
    return PartitionSpec(*out)


def remap_specs(param_specs: Dict[str, PartitionSpec],
                mapping: Dict[str, str]) -> Dict[str, PartitionSpec]:
    """Remap a whole PARAM_SPECS table onto another mesh's axis names —
    the multi-axis generalization of tp_specs: every axis named in
    `mapping` survives under its new name, every axis absent from it
    drops to replicated (remap_spec_axes semantics, applied per leaf).
    The 3D training planner (parallel/planner.plan_train) uses this to
    land the family tables — declared over ('dp','fsdp','pp','mp') —
    on a dp×fsdp×tp mesh ({'fsdp': 'fsdp', 'mp': 'tp'}: the TP split
    survives on 'tp', ZeRO-3 on 'fsdp', and 'pp' drops because the 3D
    plan scans the stacked layer axis on-chip). Shape-aware
    degrade-to-replicated stays where it always was: sharding_for(spec,
    mesh, shape) at materialization time, per leaf."""
    return {k: remap_spec_axes(s, mapping)
            for k, s in param_specs.items()}


def tp_specs(param_specs: Dict[str, PartitionSpec], src: str = "mp",
             axis: str = "tp") -> Dict[str, PartitionSpec]:
    """Derive a decode/serving tensor-parallel spec table from a
    training PARAM_SPECS table: the TP split (the reference's
    ColumnParallel/RowParallel layout on `src`, conventionally 'mp')
    survives on `axis`; every other training axis drops to replicated —
    dp/fsdp batch-shard the step, pp shards the stacked layer axis, and
    at decode the layer stack scans on-chip while the slot pool owns the
    batch. ONE derivation so the serving layout can never drift from
    the training split (models/gpt.py, models/llama.py
    SERVING_PARAM_SPECS). The single-axis case of remap_specs."""
    return remap_specs(param_specs, {src: axis})


def shard_value(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """device_put a jax array with a named sharding (Resharder analog —
    reference auto_parallel/static/reshard.py:1010; XLA inserts the actual
    collectives)."""
    s = sharding_for(spec, mesh)
    if s is None:
        return value
    return jax.device_put(value, s)


def constraint(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """with_sharding_constraint that degrades to identity outside a mesh,
    outside a trace, or on a mesh whose axis names don't match the spec
    (ALL-or-nothing, deliberately: the model-internal activation specs
    engage only on meshes built for them — a leftover ambient mesh with
    other axis names must NOT be partially adopted, e.g. an 8-device
    fsdp mesh leaking into a single-device Predictor export. The 3D
    planner-driven step doesn't rely on these hints at all: its layouts
    are pinned through make_train_step's in/out shardings)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return value
    try:
        return jax.lax.with_sharding_constraint(
            value, NamedSharding(mesh, spec))
    except Exception:
        return value
