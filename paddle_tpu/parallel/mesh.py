"""Device mesh management — the heart of the distributed design.

Reference analog: the 4-axis CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:54,140), which builds
cartesian NCCL groups per axis. TPU-native: ONE `jax.sharding.Mesh` with
named axes replaces the whole process-group zoo — XLA GSPMD emits the right
ICI/DCN collectives from shardings, so "creating a comm group" becomes
"naming a mesh axis".

Axis convention (SURVEY.md §7): ('dp', 'fsdp', 'pp', 'mp'); 'sp' (sequence /
context parallel) reuses 'mp' Megatron-style or its own axis for ring
attention; 'ep' (expert parallel) typically aliases 'fsdp'×'mp'.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_state = threading.local()


def _mesh_stack() -> List[Mesh]:
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def build_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {'dp': 2, 'mp': 4, ...}; -1 on one axis = infer."""
    devices = list(devices if devices is not None else jax.devices())
    shape = dict(axes)
    known = 1
    infer_key = None
    for k, v in shape.items():
        if v in (-1, None):
            if infer_key is not None:
                raise ValueError("only one mesh axis may be -1")
            infer_key = k
        else:
            known *= v
    if infer_key is not None:
        shape[infer_key] = len(devices) // known
    total = int(np.prod(list(shape.values())))
    if total != len(devices):
        if total < len(devices):
            devices = devices[:total]
        else:
            raise ValueError(
                f"mesh {shape} needs {total} devices, have {len(devices)}")
    arr = np.array(devices).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


def set_global_mesh(mesh: Mesh):
    _mesh_stack().clear()
    _mesh_stack().append(mesh)


def get_mesh() -> Optional[Mesh]:
    stack = _mesh_stack()
    return stack[-1] if stack else None


class use_mesh:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _mesh_stack().append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _mesh_stack().pop()
        return False


def sharding_for(spec: PartitionSpec, mesh: Optional[Mesh] = None
                 ) -> Optional[NamedSharding]:
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    # drop axes the mesh doesn't have (lets the same model run on smaller
    # meshes — e.g. TP spec on a dp-only mesh degrades to replicated)
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, (tuple, list)):
            keep = tuple(a for a in entry if a in mesh.axis_names)
            cleaned.append(keep if keep else None)
        else:
            cleaned.append(entry if entry in mesh.axis_names else None)
    return NamedSharding(mesh, PartitionSpec(*cleaned))


def shard_value(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """device_put a jax array with a named sharding (Resharder analog —
    reference auto_parallel/static/reshard.py:1010; XLA inserts the actual
    collectives)."""
    s = sharding_for(spec, mesh)
    if s is None:
        return value
    return jax.device_put(value, s)


def constraint(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """with_sharding_constraint that degrades to identity outside a mesh or
    outside a trace."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return value
    try:
        return jax.lax.with_sharding_constraint(
            value, NamedSharding(mesh, spec))
    except Exception:
        return value
