"""Auto-parallel planner: cost-model search over hybrid degrees.

Reference analog: the auto-parallel tuner stack
(python/paddle/distributed/auto_parallel/static/tuner/parallel_tuner.py:40
searching process-mesh topologies, pruned and ranked by
auto_parallel/static/cost/base_cost.py estimates). On TPU, GSPMD already
propagates shardings inside one assignment — the one thing it does NOT
do is pick the assignment. This module does: given a transformer spec, a
device count, and a chip profile, it enumerates every legal
(dp, mp, pp, fsdp) factorization, prices each with an analytical
compute + collective + pipeline-bubble + HBM model, prunes the ones that
don't fit memory, and returns the ranking.

The absolute times are nominal (a fixed MFU guess, linear collective
models); what the search relies on — and what the validation test pins —
is the ORDERING, which is driven by the relative volumes: TP pays
activation all-reduces every layer, DP pays one gradient reduction, FSDP
pays parameter all-gathers, PP pays its bubble.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = ["ChipSpec", "ModelSpec", "Plan", "TrainPlan",
           "NoFeasiblePlanError", "enumerate_plans", "plan_parallel",
           "plan_train", "degrade_plan", "spec_from_config",
           "spec_from_gpt_config", "best_mesh_axes", "plan_serving_tp"]


class NoFeasiblePlanError(ValueError):
    """No (degraded) plan fits the offered device count. `constraint`
    names the violated constraint (divisibility or HBM) so the elastic
    controller can die with a diagnosis instead of hanging on a
    collective that can never complete (parallel/elastic.py)."""

    def __init__(self, msg: str, constraint: str = ""):
        super().__init__(msg)
        self.constraint = constraint or msg


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip hardware profile (v5e-class defaults; override for other
    parts — only ratios matter for the ranking)."""
    peak_flops: float = 197e12        # bf16 MXU peak
    hbm_bytes: float = 16e9
    hbm_bw: float = 8.1e11            # bytes/s HBM stream (decode model)
    ici_bw: float = 9e10              # bytes/s per link, all-reduce model
    dcn_bw: float = 6.25e9            # bytes/s across slices (unused yet)
    mfu: float = 0.35                 # nominal achievable fraction
    coll_latency: float = 2e-6        # fixed cost per collective launch


@dataclass(frozen=True)
class ModelSpec:
    """Transformer shape the cost model prices."""
    num_layers: int
    hidden_size: int
    num_heads: int
    ffn_hidden: int
    vocab_size: int
    seq_len: int
    param_bytes_per_elem: int = 4     # f32 master params
    act_bytes_per_elem: int = 2       # bf16 activations
    remat_policy: str = "full"
    sequence_parallel: bool = True

    @property
    def block_params(self) -> int:
        d, f = self.hidden_size, self.ffn_hidden
        return self.num_layers * (4 * d * d + 2 * d * f)

    @property
    def embed_params(self) -> int:
        return (self.vocab_size + self.seq_len) * self.hidden_size

    @property
    def total_params(self) -> int:
        return self.block_params + self.embed_params


def spec_from_config(cfg) -> ModelSpec:
    """Build a ModelSpec from a single-tower model config
    (models.gpt.GPTConfig, models.bert.BertConfig, models.vit.ViTConfig):
    the transformer fields are duck-typed; ViT-style configs derive the
    sequence length from the patch grid. Composite dual-tower configs
    (ErnieViLConfig) don't fit one transformer spec — plan a tower
    explicitly (spec_from_config(cfg.text) / (cfg.vision))."""
    if hasattr(cfg, "text") and hasattr(cfg, "vision"):
        raise ValueError(
            f"{type(cfg).__name__} is a dual-tower composite; plan one "
            "tower at a time: spec_from_config(cfg.text) or "
            "spec_from_config(cfg.vision)")
    seq = getattr(cfg, "max_seq_len", None)
    if seq is None and hasattr(cfg, "num_patches"):
        seq = cfg.num_patches + 1                          # + [CLS]
    elif seq is None and hasattr(cfg, "image_size") and hasattr(
            cfg, "patch_size"):
        seq = (cfg.image_size // cfg.patch_size) ** 2 + 1
    if seq is None:
        raise ValueError(
            f"{type(cfg).__name__} has neither max_seq_len nor an "
            "image/patch geometry to derive a sequence length from")
    return ModelSpec(
        num_layers=cfg.num_layers, hidden_size=cfg.hidden_size,
        num_heads=cfg.num_heads, ffn_hidden=cfg.ffn_hidden,
        vocab_size=getattr(cfg, "vocab_size", 0), seq_len=seq,
        remat_policy=(getattr(cfg, "remat_policy", "full")
                      if getattr(cfg, "remat", False) else "none"),
        sequence_parallel=getattr(cfg, "sequence_parallel", False))


# historical name (round-5 introduced the planner GPT-first)
spec_from_gpt_config = spec_from_config


# How many residual-sized buffers per layer survive the forward, by remat
# policy (drives the activation-memory estimate; calibrated against the
# ablation notes in BASELINE.md: no-remat ~33 GB vs full-remat ~11 GB
# temp on the 350M sweep shapes).
_ACT_BUFFERS = {"full": 2.0, "dots": 9.0, "dots_flash": 10.0,
                "offload_dots": 3.0, "all_but_mlp": 14.0, "none": 20.0}


@dataclass
class Plan:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    fsdp: int = 1
    microbatches: int = 1
    # latency-hiding collectives (docs/parallel_training.md §Collective
    # overlap): the pp step double-buffers the ZeRO-3 layer gather and
    # the GSPMD step gets the async-collective XLA flags; priced as a
    # deeper fsdp discount in _estimate. Off by default — adoption is
    # evidence-gated, never assumed.
    overlap: bool = False
    step_s: float = float("inf")
    mem_bytes: float = 0.0
    fits: bool = True
    breakdown: Dict[str, float] = field(default_factory=dict)

    @property
    def n_devices(self) -> int:
        return self.dp * self.mp * self.pp * self.fsdp

    def mesh_axes(self) -> Dict[str, int]:
        axes = {}
        if self.dp > 1 or (self.mp == self.pp == self.fsdp == 1):
            axes["dp"] = self.dp
        if self.fsdp > 1:
            axes["fsdp"] = self.fsdp
        if self.pp > 1:
            axes["pp"] = self.pp
        if self.mp > 1:
            axes["mp"] = self.mp
        return axes

    def __repr__(self):
        keys = f"dp{self.dp}_mp{self.mp}_pp{self.pp}_fsdp{self.fsdp}"
        if self.pp > 1:
            keys += f"_mb{self.microbatches}"
        ms = self.step_s * 1e3
        gb = self.mem_bytes / 1e9
        return (f"Plan({keys}, est {ms:.{3 if ms < 1 else 1}f} ms, "
                f"mem {gb:.{2 if gb < 1 else 1}f} GB"
                + ("" if self.fits else ", OOM") + ")")


def _ring_factor(n: int) -> float:
    """Per-chip all-reduce volume multiplier: ring moves 2(n-1)/n of the
    buffer through each chip."""
    return 2.0 * (n - 1) / n if n > 1 else 0.0


# Fraction of the ZeRO-3 gather/scatter volume still EXPOSED on the
# critical path when plan.overlap double-buffers the per-layer gather
# (layer i+1's all-gather issues under layer i's compute; the transpose
# reduce-scatter overlaps the backward the same way). One layer's
# gather — the un-prefetchable first one — plus scheduling slack; kept
# a single named constant so cost_model.train_step_ledger prices the
# coll_fsdp phase with the SAME number (tools/train_attrib --compare
# cross-checks the two).
FSDP_OVERLAP_EXPOSED = 0.4


def _estimate(plan: Plan, spec: ModelSpec, global_batch: int,
              chip: ChipSpec) -> Plan:
    """Fill in step_s / mem_bytes / fits for one assignment."""
    dp, mp, pp, fsdp = plan.dp, plan.mp, plan.pp, plan.fsdp
    L, D, S = spec.num_layers, spec.hidden_size, spec.seq_len
    V, F = spec.vocab_size, spec.ffn_hidden
    tokens = global_batch * S
    b_local = max(global_batch // (dp * fsdp), 1)   # batch shards dp×fsdp
    tok_local = b_local * S
    abytes = spec.act_bytes_per_elem

    # ---- compute: fwd 2*P_used*tokens + attention, bwd 2x fwd --------
    matmul_flops = 2 * (spec.block_params + 2 * V * D) * tokens
    attn_flops = 4 * tokens * S * D * L            # QK^T + PV, non-causal
    remat_extra = {"full": 1.0 / 3.0, "dots": 0.15, "dots_flash": 0.1,
                   "offload_dots": 0.2, "all_but_mlp": 0.12,
                   "none": 0.0}.get(spec.remat_policy, 1.0 / 3.0)
    flops = (matmul_flops + attn_flops) * 3.0 * (1.0 + remat_extra / 3.0)
    compute_s = flops / plan.n_devices / (chip.peak_flops * chip.mfu)
    # pipeline bubble: (pp-1) idle slots per m microbatch slots
    if pp > 1:
        compute_s *= 1.0 + (pp - 1) / max(plan.microbatches, 1)

    # ---- communication (per chip, bytes / ici_bw) --------------------
    # TP: 2 activation all-reduces fwd + 2 bwd per layer (or the
    # reduce-scatter/all-gather pair under SP — same moved volume)
    tp_bytes = (_ring_factor(mp) * 4 * L * tok_local * D * abytes
                if mp > 1 else 0.0)
    # DP: one gradient all-reduce of this chip's param shard (f32)
    shard_params = spec.total_params / (mp * pp * fsdp)
    dp_bytes = _ring_factor(dp) * shard_params * 4
    # FSDP/ZeRO-3: all-gather params in fwd and again in bwd, reduce-
    # scatter grads — ~3 all-gather-sized moves of the fsdp shard
    fsdp_bytes = (3.0 * (fsdp - 1) / fsdp
                  * (spec.total_params / (mp * pp)) * abytes
                  if fsdp > 1 else 0.0)
    # PP: boundary activations each way per microbatch
    pp_bytes = (2 * plan.microbatches
                * (tok_local / max(plan.microbatches, 1)) * D * abytes
                * (pp - 1) / pp if pp > 1 else 0.0)
    # overlap discounts: DP grad reduction overlaps the backward well;
    # TP all-reduces sit on the critical path. Collective LAUNCHES also
    # carry a fixed latency — TP pays 4 per layer on the critical path,
    # DP's gradient reduction fuses into a handful, FSDP buckets too —
    # which is what prices TP out for small models where byte volumes
    # alone would call it free.
    tp_ops = 4 * L if mp > 1 else 0
    dp_ops = 2 if dp > 1 else 0
    fsdp_ops = 3 if fsdp > 1 else 0
    pp_ops = 2 * plan.microbatches if pp > 1 else 0
    # latency-hiding collectives (plan.overlap): the double-buffered
    # ZeRO-3 gather issues layer i+1's all-gather while layer i
    # computes, so only the un-hideable fraction of the fsdp volume
    # stays on the critical path (FSDP_OVERLAP_EXPOSED of the default
    # 0.6 exposure). TP all-reduces stay at 1.0 — collective-matmul
    # hides them only on real TPU rungs, and pricing must not promise
    # what the CPU rung can't measure.
    fsdp_disc = (0.6 * FSDP_OVERLAP_EXPOSED if plan.overlap else 0.6)
    comm_s = ((tp_bytes * 1.0 + dp_bytes * 0.3 + fsdp_bytes * fsdp_disc
               + pp_bytes * 0.5) / chip.ici_bw
              + (tp_ops + dp_ops + fsdp_ops + pp_ops)
              * chip.coll_latency)

    # ---- memory ------------------------------------------------------
    # ONE home for the per-chip HBM model: cost_model.train_memory_
    # ledger attributes the same bytes to named components (params /
    # grads / adam m+v, remat activation working set, logits chunk,
    # overlap prefetch) and profiler/mem_audit diffs that ledger
    # against XLA's compiled accounting — _estimate consumes the
    # ledger's total so the gate and the audit can never drift apart.
    from ..cost_model import train_memory_ledger
    led = train_memory_ledger(spec, plan, global_batch)
    comp = led["components"]
    mem = led["total"]
    plan.step_s = compute_s + comm_s
    plan.mem_bytes = mem
    plan.fits = mem <= 0.9 * chip.hbm_bytes
    plan.breakdown = {
        "compute_s": compute_s, "tp_s": tp_bytes / chip.ici_bw,
        "dp_s": dp_bytes * 0.3 / chip.ici_bw,
        "fsdp_s": fsdp_bytes * fsdp_disc / chip.ici_bw,
        "pp_s": pp_bytes * 0.5 / chip.ici_bw,
        "state_gb": (comp["params"] + comp["grads"] + comp["adam_m"]
                     + comp["adam_v"]) / 1e9,
        "act_gb": comp["activations"] / 1e9,
    }
    return plan


def _factorizations(n: int) -> List[tuple]:
    out = []
    for dp in (d for d in range(1, n + 1) if n % d == 0):
        rem = n // dp
        for mp in (d for d in range(1, rem + 1) if rem % d == 0):
            rem2 = rem // mp
            for pp in (d for d in range(1, rem2 + 1) if rem2 % d == 0):
                out.append((dp, mp, pp, rem2 // pp))
    return out


def _coerce_spec(model) -> ModelSpec:
    """ONE home for the ModelSpec-or-model-config dispatch
    (plan_parallel, enumerate_plans, and cost_model.rank_parallel_plans
    all take either)."""
    return model if isinstance(model, ModelSpec) \
        else spec_from_config(model)


def enumerate_plans(spec, n_devices: int, global_batch: int,
                    chip: Optional[ChipSpec] = None,
                    microbatches: Optional[int] = None,
                    max_mp: Optional[int] = None) -> List[Plan]:
    """All legal assignments, priced, sorted best-first (OOM plans sink
    to the bottom, still priced so the caller can see why). `spec` is a
    ModelSpec or a GPTConfig."""
    spec = _coerce_spec(spec)
    chip = chip or ChipSpec()
    plans = []
    for dp, mp, pp, fsdp in _factorizations(n_devices):
        # legality: mp divides heads and ffn; pp divides layers;
        # dp*fsdp divides the global batch
        if spec.num_heads % mp or spec.ffn_hidden % mp:
            continue
        if max_mp and mp > max_mp:
            continue
        if spec.num_layers % pp:
            continue
        if global_batch % (dp * fsdp):
            continue
        mb = microbatches or (4 * pp if pp > 1 else 1)
        mb = min(mb, max(global_batch // (dp * fsdp), 1))
        plans.append(_estimate(
            Plan(dp=dp, mp=mp, pp=pp, fsdp=fsdp, microbatches=mb),
            spec, global_batch, chip))
    plans.sort(key=lambda p: (not p.fits, p.step_s))
    return plans


def _diagnose_empty(spec: ModelSpec, n_devices: int, global_batch: int,
                    max_mp: Optional[int],
                    max_pp: Optional[int] = None) -> str:
    """Why enumerate_plans returned nothing: re-walk every factorization
    and name the constraint(s) that pruned it, so the caller's error
    says WHICH divisibility failed instead of a generic 'no legal
    assignment' (the _factorizations edge cases — prime device counts,
    a global batch no dp×fsdp split divides, single-device — all land
    here with an actionable message). `max_pp` restricts the walk the
    same way the caller restricted its search (plan_train excludes
    pp>1), so the diagnosis prices exactly the space that came up
    empty — a pp=8 escape hatch the caller forbids must not mask the
    real batch/heads blocker."""
    facts = [f for f in _factorizations(n_devices)
             if max_pp is None or f[2] <= max_pp]
    if not facts:
        return f"n_devices={n_devices} has no factorization (must be >= 1)"
    reasons = []
    mp_legal = [mp for _, mp, _, _ in facts
                if spec.num_heads % mp == 0 and spec.ffn_hidden % mp == 0
                and not (max_mp and mp > max_mp)]
    if not mp_legal:
        reasons.append(
            f"num_heads={spec.num_heads}/ffn_hidden={spec.ffn_hidden} "
            f"admit no mp degree dividing n_devices={n_devices}"
            + (f" under max_mp={max_mp}" if max_mp else ""))
    pp_legal = [pp for _, _, pp, _ in facts if spec.num_layers % pp == 0]
    if not pp_legal:
        reasons.append(
            f"num_layers={spec.num_layers} admits no pp degree dividing "
            f"n_devices={n_devices}")
    # the batch constraint interacts with the others: only dp×fsdp
    # splits that survive the mp/pp pruning count
    dpxfsdp = sorted({dp * fsdp for dp, mp, pp, fsdp in facts
                      if spec.num_heads % mp == 0
                      and spec.ffn_hidden % mp == 0
                      and not (max_mp and mp > max_mp)
                      and spec.num_layers % pp == 0})
    if dpxfsdp and not any(global_batch % d == 0 for d in dpxfsdp):
        reasons.append(
            f"global_batch={global_batch} is not divisible by any legal "
            f"dp*fsdp split of {n_devices} devices "
            f"(candidates: {dpxfsdp})")
    return "; ".join(reasons) or "every assignment was pruned"


def plan_parallel(cfg_or_spec, n_devices: int, global_batch: int,
                  chip: Optional[ChipSpec] = None, **kw) -> Plan:
    """The best assignment for a GPTConfig or ModelSpec (the reference
    parallel_tuner's `tune()` surface collapsed to a function). When no
    assignment is legal the error names the failing divisibility
    constraint (heads/ffn vs mp, layers vs pp, global batch vs
    dp×fsdp)."""
    spec = _coerce_spec(cfg_or_spec)
    plans = enumerate_plans(spec, n_devices, global_batch, chip, **kw)
    if not plans:
        raise ValueError(
            f"no legal (dp, mp, pp, fsdp) assignment for {n_devices} "
            f"devices: "
            + _diagnose_empty(spec, n_devices, global_batch,
                              kw.get("max_mp")))
    return plans[0]


# ------------------------------------------------------- executable plans
@dataclass
class TrainPlan:
    """An EXECUTABLE 3D/4D assignment: what models.facade.make_train_step
    (mesh=, plan=) consumes. `axes` materializes through
    parallel.mesh.build_mesh; `specs` is the family's module-level
    PARAM_SPECS table remapped onto those axes (parallel.mesh.remap_specs
    — the TP split lands on `tp`, ZeRO-3 on `fsdp`; 'pp' drops to the
    on-chip layer scan in the 3D formulation, but SURVIVES as the
    stage-chunk axis when the plan carries pp>1: the stacked layer dim
    shards over the 'pp' mesh axis and the step runs the 1F1B
    microbatched pipeline of parallel/pipeline_train.py);
    `batch_axes` names the axes the global batch shards over (dp×fsdp).
    `plan` keeps the priced cost-model row the choice came from."""
    axes: Dict[str, int]
    mapping: Dict[str, str]
    batch_axes: tuple
    plan: Plan
    specs: Optional[Dict] = None
    # latency-hiding collectives knob (mirrors Plan.overlap): the
    # facade reads it as the default for make_train_step(overlap=None)
    overlap: bool = False

    @property
    def name(self) -> str:
        return "_".join(f"{a}{n}" for a, n in self.axes.items())

    @property
    def pp(self) -> int:
        return int(self.axes.get("pp", 1))

    @property
    def microbatches(self) -> int:
        return int(getattr(self.plan, "microbatches", 1) or 1)

    def build_mesh(self, devices=None):
        from .mesh import build_mesh
        return build_mesh(self.axes, devices=devices)

    def batch_spec(self, ndim: int = 2):
        """PartitionSpec for a batch leaf: leading dim over dp×fsdp,
        the rest replicated."""
        from jax.sharding import PartitionSpec as P
        return P(tuple(self.batch_axes), *([None] * (ndim - 1)))

    def __repr__(self):
        return f"TrainPlan({self.name}, {self.plan!r})"


def _resolve_param_specs(cfg) -> Optional[Dict]:
    """The module-level PARAM_SPECS table of the config's model family
    (GPTConfig -> models.gpt.PARAM_SPECS, LlamaConfig -> models.llama's,
    ...): the family declares its sharding next to its init/forward, so
    the planner never hardcodes a layout. None for bare ModelSpecs and
    configs whose module declares no table — pass param_specs= then."""
    if isinstance(cfg, ModelSpec):
        return None
    import sys
    mod = sys.modules.get(type(cfg).__module__)
    return getattr(mod, "PARAM_SPECS", None)


def _pick_microbatches(b_local: int, pp: int) -> Optional[int]:
    """The microbatch count a pp>1 plan runs: the largest divisor of the
    per-(dp×fsdp)-shard batch not exceeding 4·pp (deeper pipelines want
    more microbatches to amortize the (pp-1)/m bubble; past ~4·pp the
    returns flatten while the per-microbatch tensors shrink below
    efficient tile sizes). None when the shard admits no split (a
    1-row shard cannot microbatch)."""
    for m in range(min(int(b_local), 4 * pp), 1, -1):
        if b_local % m == 0:
            return m
    return None


def _pp_manual_constraints(spec: ModelSpec, dp: int, fsdp: int, tp: int,
                           pp: int, global_batch: int,
                           microbatches: Optional[int] = None
                           ) -> tuple:
    """(problems, microbatches) for a pp>1 assignment. The pipelined
    step is a FULL-manual shard_map (parallel/pipeline_train.py — this
    container's legacy GSPMD fatally aborts partial-auto shard_map, so
    every axis is hand-partitioned), which cannot shape-degrade per
    leaf the way GSPMD does; the extra divisibilities are therefore
    plan-level legality, each named."""
    problems = []
    if spec.num_layers % pp:
        problems.append(f"pp={pp} does not divide num_layers="
                        f"{spec.num_layers} (stage chunking needs equal "
                        "layer chunks per stage)")
    if tp > 1 and spec.vocab_size and spec.vocab_size % tp:
        problems.append(f"tp={tp} does not divide vocab_size="
                        f"{spec.vocab_size} (the pp step's manual "
                        "vocab-parallel embedding/head)")
    if fsdp > 1 and spec.hidden_size % fsdp:
        problems.append(f"fsdp={fsdp} does not divide hidden_size="
                        f"{spec.hidden_size} (the pp step's manual "
                        "ZeRO-3 weight gather)")
    b_local = global_batch // max(dp * fsdp, 1) \
        if global_batch % max(dp * fsdp, 1) == 0 else 0
    mb = microbatches or (_pick_microbatches(b_local, pp) if b_local
                          else None)
    if not mb or mb < 2 or (b_local and b_local % mb):
        problems.append(
            f"microbatches={microbatches or mb} does not split the "
            f"per-shard batch global_batch/(dp*fsdp)="
            f"{b_local or '<indivisible>'} into >=2 equal microbatches "
            f"(pp={pp} needs a 1F1B schedule)")
        mb = mb or 0
    return problems, int(mb or 0)


def plan_train(cfg_or_spec, n_devices: int, global_batch: int,
               chip: Optional[ChipSpec] = None, dp: Optional[int] = None,
               fsdp: Optional[int] = None, tp: Optional[int] = None,
               pp: Optional[int] = None,
               microbatches: Optional[int] = None,
               tp_axis: str = "tp", param_specs: Optional[Dict] = None,
               overlap: bool = False, **kw) -> TrainPlan:
    """The executable dp×fsdp×tp(×pp) assignment for a model config:
    search the cost model, then emit the {axes -> PartitionSpec tree}
    contract: mesh axes for build_mesh, the family PARAM_SPECS remapped
    onto them, and the dp×fsdp batch spec. Pass explicit degrees
    (dp/fsdp/tp, optionally pp + microbatches) to skip the search;
    illegal explicit degrees raise naming the violated constraint, same
    as plan_parallel.

    Pipeline parallelism (docs/parallel_training.md): the search
    prefers pp=1 (the 3D step scans the stacked layer axis on-chip —
    no bubble, no boundary traffic) and emits pp>1 ONLY through the
    HBM gate: when no dp×fsdp×tp assignment fits per-chip memory even
    at fsdp=max, stage-chunking the layer stack over a 'pp' mesh axis
    is the remaining lever (it divides per-stage weights AND
    activations, and microbatching divides the logit working set).
    A pp>1 plan carries the extra manual-step legality constraints
    (_pp_manual_constraints) and a microbatch count with
    (pp-1)/microbatches priced as its bubble.

    Also publishes the chosen degrees as the `train.plan.*` monitor
    gauge family (docs/observability.md) so a run's telemetry stream
    records WHICH plan it executed."""
    spec = _coerce_spec(cfg_or_spec)
    chip = chip or ChipSpec()
    if any(d is not None for d in (dp, fsdp, tp, pp)):
        dp, fsdp, tp, pp = (int(d or 1) for d in (dp, fsdp, tp, pp))
        problems = []
        if dp * fsdp * tp * pp != n_devices:
            wanted = (f"dp*fsdp*tp = {dp}*{fsdp}*{tp}" if pp == 1 else
                      f"dp*fsdp*tp*pp = {dp}*{fsdp}*{tp}*{pp}")
            problems.append(f"{wanted} = {dp * fsdp * tp * pp} != "
                            f"n_devices={n_devices}")
        if spec.num_heads % tp or spec.ffn_hidden % tp:
            problems.append(f"tp={tp} does not divide num_heads="
                            f"{spec.num_heads}/ffn_hidden="
                            f"{spec.ffn_hidden}")
        if global_batch % (dp * fsdp):
            problems.append(f"global_batch={global_batch} is not "
                            f"divisible by dp*fsdp={dp * fsdp}")
        mb = 1
        if pp > 1:
            pp_problems, mb = _pp_manual_constraints(
                spec, dp, fsdp, tp, pp, global_batch, microbatches)
            problems.extend(pp_problems)
        elif microbatches and microbatches > 1:
            problems.append(f"microbatches={microbatches} needs pp>1 "
                            "(the 3D step has no pipeline to fill)")
        if problems:
            # NoFeasiblePlanError IS a ValueError (historical callers
            # keep matching); `constraint` names the violation for the
            # elastic controller's diagnosis path
            raise NoFeasiblePlanError(
                f"illegal {'4D' if pp > 1 else '3D'} plan: "
                + "; ".join(problems),
                constraint="; ".join(problems))
        best = _estimate(Plan(dp=dp, mp=tp, pp=pp, fsdp=fsdp,
                              microbatches=mb, overlap=overlap),
                         spec, global_batch, chip)
    else:
        plans = enumerate_plans(spec, n_devices, global_batch, chip, **kw)
        pp1 = [p for p in plans if p.pp == 1]
        best = next((p for p in pp1 if p.fits), None)
        if best is None:
            # HBM gate: nothing fits flat, even at fsdp=max — the priced
            # enumeration may emit pp>1 (stage chunks divide per-chip
            # layer weights AND activations; microbatches divide the
            # logit working set). Only manual-step-legal candidates
            # qualify; the microbatch count is re-picked per candidate
            # so the priced bubble is the one the step will run.
            for cand in (p for p in plans if p.pp > 1 and p.fits):
                probs, mb = _pp_manual_constraints(
                    spec, cand.dp, cand.fsdp, cand.mp, cand.pp,
                    global_batch)
                if probs:
                    continue
                priced = _estimate(
                    Plan(dp=cand.dp, mp=cand.mp, pp=cand.pp,
                         fsdp=cand.fsdp, microbatches=mb),
                    spec, global_batch, chip)
                # re-CHECK fits with the REAL microbatch count: the
                # enumeration priced this candidate at ~4·pp
                # microbatches, and a smaller legal mb grows the logit
                # working set — an OOM re-estimate must not win over a
                # deeper candidate whose mb is viable
                if priced.fits:
                    best = priced
                    break
        if best is None and pp1:
            best = pp1[0]            # least-bad OOM 3D plan, still priced
        if best is None:
            raise ValueError(
                f"no legal (dp, fsdp, tp[, pp]) assignment for "
                f"{n_devices} devices: "
                + _diagnose_empty(spec, n_devices, global_batch,
                                  kw.get("max_mp")))
    if overlap and not best.overlap:
        # the search priced candidates without overlap (the knob never
        # changes WHICH plan wins — it scales one phase); re-price the
        # winner so step_s/breakdown reflect the hidden fsdp volume
        best = _estimate(
            Plan(dp=best.dp, mp=best.mp, pp=best.pp, fsdp=best.fsdp,
                 microbatches=best.microbatches, overlap=True),
            spec, global_batch, chip)
    axes = {"dp": best.dp, "fsdp": best.fsdp, tp_axis: best.mp}
    mapping = {"dp": "dp", "fsdp": "fsdp", "mp": tp_axis}
    if best.pp > 1:
        # the stacked layer axis SURVIVES as the stage-chunk axis: the
        # remapped specs shard it over 'pp' and the mesh carries all
        # four axes (degree-1 included — the manual step names them all)
        axes["pp"] = best.pp
        mapping["pp"] = "pp"
    if param_specs is None:
        param_specs = _resolve_param_specs(cfg_or_spec)
    specs = None
    if param_specs is not None:
        from .mesh import remap_specs
        specs = remap_specs(param_specs, mapping)
    from ..profiler import monitor
    for ax, n in axes.items():
        monitor.gauge(f"train.plan.{ax}").set(n)
    monitor.gauge("train.plan.n_devices").set(best.n_devices)
    # the pp family publishes UNCONDITIONALLY: after an elastic degrade
    # collapses pp>1 back onto the layer scan, stale train.plan.pp /
    # microbatches / bubble_fraction gauges would keep advertising the
    # old 4D plan in telemetry_report's train_plan block. pp=1 resets
    # them (bubble 0.0 = no pipeline; the pp>1 step's measured value
    # overwrites at its warmup).
    monitor.gauge("train.plan.pp").set(best.pp)
    monitor.gauge("train.plan.microbatches").set(
        best.microbatches if best.pp > 1 else 1)
    if best.pp <= 1:
        monitor.gauge("train.bubble_fraction").set(0.0)
    return TrainPlan(axes=axes, mapping=mapping,
                     batch_axes=("dp", "fsdp"), plan=best, specs=specs,
                     overlap=bool(overlap))


def _divisors_desc(n: int) -> List[int]:
    return [d for d in range(n, 0, -1) if n % d == 0]


def degrade_plan(cfg_or_spec, old: TrainPlan, n_surviving: int,
                 global_batch: int, chip: Optional[ChipSpec] = None,
                 tp_axis: str = "tp",
                 param_specs: Optional[Dict] = None) -> TrainPlan:
    """Degrade `old` onto at most `n_surviving` devices after device
    loss (parallel/elastic.py). Preference order: **dp gives way first,
    then fsdp, and tp AND pp are held** — re-slicing the TP split would
    change the per-layer collective pattern and the head partitioning,
    and re-chunking the pipeline stages would re-slice every stacked
    leaf's stage windows AND change the 1F1B schedule (both the most
    expensive reshards), while shrinking dp/fsdp only re-shards the
    batch and the ZeRO-3 windows, which the checkpoint manifest
    re-slices for free (docs/fault_tolerance.md). Candidates rank
    largest-surviving-world-first so the degrade strands as few chips
    as possible; when no held candidate is legal (e.g. tp·pp itself
    exceeds the survivors) the full search runs on every world size
    down from `n_surviving` — collapsing pipeline stages (pp shrinks
    toward the layer scan) only when the survivors cannot form the old
    stage grid.

    Raises NoFeasiblePlanError naming the violated constraint when
    nothing fits — divisibility (including the pp stage-grid
    constraint) via the `_diagnose_empty` walk, HBM with the per-chip
    state bytes spelled out."""
    spec = _coerce_spec(cfg_or_spec)
    chip = chip or ChipSpec()
    if n_surviving < 1:
        raise NoFeasiblePlanError(
            f"no surviving devices (n_surviving={n_surviving})",
            constraint=f"n_surviving={n_surviving} < 1")
    dp0 = old.axes.get("dp", 1)
    fsdp0 = old.axes.get("fsdp", 1)
    tp0 = old.axes.get(tp_axis, 1)
    pp0 = old.axes.get("pp", 1)
    oom = []                      # legal-but-OOM candidates, for the error
    # tp·pp-held lattice: every (dp' | dp, fsdp' | fsdp) shrink keeps
    # the batch divisibility old already satisfied; rank by total desc,
    # then PREFER the larger fsdp' (i.e. shrink dp before fsdp).
    # Candidates are priced with _estimate only; plan_train (which
    # publishes the train.plan.* gauges) runs once, for the winner.
    cands = sorted(((dp, fsdp) for dp in _divisors_desc(dp0)
                    for fsdp in _divisors_desc(fsdp0)
                    if dp * fsdp * tp0 * pp0 <= n_surviving),
                   key=lambda c: (-(c[0] * c[1] * tp0 * pp0), -c[1],
                                  -c[0]))
    for dp, fsdp in cands:
        mb = 1
        if pp0 > 1:
            probs, mb = _pp_manual_constraints(spec, dp, fsdp, tp0, pp0,
                                               global_batch)
            if probs:
                continue          # this shrink can't microbatch — skip
        priced = _estimate(Plan(dp=dp, mp=tp0, pp=pp0, fsdp=fsdp,
                                microbatches=mb), spec, global_batch,
                           chip)
        if priced.fits:
            return plan_train(cfg_or_spec, dp * fsdp * tp0 * pp0,
                              global_batch, chip=chip, dp=dp, fsdp=fsdp,
                              tp=tp0, pp=pp0,
                              microbatches=mb if pp0 > 1 else None,
                              tp_axis=tp_axis, param_specs=param_specs,
                              overlap=getattr(old, "overlap", False))
        oom.append(priced)
    # tp/pp cannot be held (or every held candidate is OOM): full
    # search, largest world first — pp=1 plans preferred (stage
    # collapse back onto the layer scan), pp>1 only through the same
    # HBM-gate legality plan_train's search applies
    for n in range(n_surviving, 0, -1):
        plans = enumerate_plans(spec, n, global_batch, chip)
        oom.extend(p for p in plans if p.pp == 1 and not p.fits)
        best = next((p for p in plans if p.pp == 1 and p.fits), None)
        mb = None
        if best is None:
            for p in (q for q in plans if q.pp > 1 and q.fits):
                probs, cand_mb = _pp_manual_constraints(
                    spec, p.dp, p.fsdp, p.mp, p.pp, global_batch)
                if probs:
                    continue
                priced = _estimate(Plan(dp=p.dp, mp=p.mp, pp=p.pp,
                                        fsdp=p.fsdp,
                                        microbatches=cand_mb),
                                   spec, global_batch, chip)
                # same re-check as plan_train's HBM gate: fits must
                # hold at the REAL microbatch count
                if priced.fits:
                    best, mb = priced, cand_mb
                    break
        if best is not None:
            return plan_train(cfg_or_spec, n, global_batch, chip=chip,
                              dp=best.dp, fsdp=best.fsdp, tp=best.mp,
                              pp=best.pp, microbatches=mb,
                              tp_axis=tp_axis, param_specs=param_specs,
                              overlap=getattr(old, "overlap", False))
    if oom:
        best = min(oom, key=lambda p: p.mem_bytes)
        raise NoFeasiblePlanError(
            f"no degraded plan fits {n_surviving} surviving devices: "
            f"best candidate {best!r} needs {best.mem_bytes / 1e9:.2f} "
            f"GB/chip > 0.9*hbm_bytes = {0.9 * chip.hbm_bytes / 1e9:.2f}"
            f" GB even at max sharding",
            constraint=f"hbm: {best.mem_bytes / 1e9:.2f} GB/chip > "
                       f"{0.9 * chip.hbm_bytes / 1e9:.2f} GB")
    reason = _diagnose_empty(spec, n_surviving, global_batch, None)
    raise NoFeasiblePlanError(
        f"no legal degraded (dp, fsdp, tp, pp) assignment for "
        f"{n_surviving} surviving devices: {reason}", constraint=reason)


def plan_serving_tp(cfg_or_spec, n_devices: int, num_slots: int = 8,
                    max_len: Optional[int] = None,
                    chip: Optional[ChipSpec] = None,
                    cache_bytes_per_elem: int = 2) -> Dict[str, int]:
    """Pick the tensor-parallel degree for the serving decode tick
    (inference/serving.py mesh= / tools/bench_serving.py --tp): the
    tick is weight-BANDWIDTH bound — every decode step streams every
    weight byte once, plus the live KV pool — so tp divides the bytes
    each chip streams, while paying ~2 activation all-reduces per
    layer whose tiny [slots, D] payloads make the fixed collective
    LAUNCH latency the real price (the same term that prices TP out
    of small-model training above). Memory is a hard gate: weights +
    the KV pool must fit per chip, so a model bigger than one chip
    FORCES tp > 1 — the "models bigger than one chip" half of ROADMAP
    item 3. Returns mesh axes for parallel.mesh.build_mesh, e.g.
    {'tp': 4}; only degrees dividing both n_devices and num_heads are
    considered (head-sharded attention). Consumers: the serving bench
    (--tp adoption), and inference/autoscale.EnginePreemptGuard,
    which re-runs this pricing on the SURVIVOR count after a device
    lease goes stale to pick the degraded tp degree."""
    spec = _coerce_spec(cfg_or_spec)
    chip = chip or ChipSpec()
    S = max_len or spec.seq_len
    # per-tick streamed bytes: weights in the serving compute dtype +
    # the worst-case live KV pool (dense-equivalent envelope). The
    # formulas live in cost_model.serving_memory_ledger (the ONE home
    # profiler/mem_audit diffs against compiled accounting); the gate
    # envelope is weights + kv_pool — decode scratch rides inside the
    # 10% headroom the 0.9 factor already reserves.
    from ..cost_model import serving_memory_ledger
    led = serving_memory_ledger(
        spec, layout="dense", quant="off", num_slots=num_slots,
        max_len=S, cache_bytes_per_elem=cache_bytes_per_elem,
        dtype_bytes=spec.act_bytes_per_elem)
    w_bytes = led["components"]["weights"]
    kv_bytes = led["components"]["kv_pool_device"]
    degrees = [d for d in range(1, n_devices + 1)
               if n_devices % d == 0 and spec.num_heads % d == 0]
    best, best_t, best_fits = None, float("inf"), False
    for tp in degrees:
        shard = (w_bytes + kv_bytes) / tp
        fits = shard <= 0.9 * chip.hbm_bytes
        ar_bytes = (_ring_factor(tp) * 2 * spec.num_layers * num_slots
                    * spec.hidden_size * spec.act_bytes_per_elem)
        t = (shard / chip.hbm_bw + ar_bytes / chip.ici_bw
             + (2 * spec.num_layers * chip.coll_latency
                if tp > 1 else 0.0))
        # a non-fitting degree only wins over another non-fitting one
        if best is None or (not fits, t) < (not best_fits, best_t):
            best, best_t, best_fits = tp, t, fits
    return {"tp": best}      # tp=1 always qualifies, so best is set


def best_mesh_axes(param_count: int, n_devices: int,
                   chip: Optional[ChipSpec] = None) -> Dict[str, int]:
    """Generic-model auto mode for Engine: with no layer structure to
    reason about, the only sound choice is dp vs fsdp — shard the
    parameter state across fsdp only when the optimizer state would not
    fit replicated (fsdp costs all-gathers every step; dp's gradient
    reduction overlaps the backward).

    `param_count` is the parameter ELEMENT count: optimizer state is
    priced as f32 master + grad + adam m/v (16 bytes/elem) regardless of
    the model's storage dtype. fsdp only takes degrees that divide
    n_devices — a non-divisor would silently strand devices."""
    chip = chip or ChipSpec()
    state = param_count * 16
    if state <= 0.5 * chip.hbm_bytes or n_devices == 1:
        return {"dp": n_devices}
    divisors = [d for d in range(2, n_devices + 1) if n_devices % d == 0]
    fsdp = next((d for d in divisors
                 if state / d <= 0.5 * chip.hbm_bytes),
                divisors[-1] if divisors else 1)
    axes = {}
    if n_devices // fsdp > 1:
        axes["dp"] = n_devices // fsdp
    axes["fsdp"] = fsdp
    return axes
