"""ZeRO-style sharding (group_sharded).

Reference analog: DygraphShardingOptimizer (stage 1,
meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:29),
GroupShardedStage2/GroupShardedOptimizerStage2
(group_sharded_stage2.py:46, group_sharded_optimizer_stage2.py:53),
GroupShardedStage3 (group_sharded_stage3.py:59) and the public
paddle.distributed.sharding.group_sharded_parallel API
(distributed/sharding/group_sharded.py).

TPU-native: the three stages collapse into sharding declarations over the
'fsdp' (or 'dp') mesh axis —
  stage 1  = optimizer state sharded   (moments P('fsdp'))
  stage 2  = + gradients sharded       (XLA reduce-scatters grads)
  stage 3  = + parameters sharded      (XLA all-gathers at use)
XLA GSPMD derives the reduce-scatter/all-gather schedule from those specs,
which is exactly the hand-written choreography of the reference's stage-2/3
wrappers. `offload=True` places optimizer state in the host memory space
(PJRT memory kinds, NamedSharding(..., memory_kind="pinned_host")) — the
reference's CPUAdam-style offload, with XLA emitting the H2D/D2H transfers
around the update instead of a hand-written pinned-buffer pump.
"""
from __future__ import annotations

import warnings

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from .mesh import get_mesh, build_mesh, set_global_mesh, shard_value


def _fsdp_axis(mesh):
    if mesh is None:
        return None
    for ax in ("fsdp", "dp"):
        if ax in mesh.axis_names and mesh.shape[ax] > 1:
            return ax
    return None


def _shardable(p, n):
    return p.ndim >= 1 and p.shape[0] % n == 0 and p.size >= 1024


def host_memory_kind():
    """The host memory space's name when this backend supports memory
    kinds (TPU PJRT: 'pinned_host'), else None."""
    try:
        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:
        return None
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return None


def device_memory_kind():
    """The backend's DEFAULT (compute) memory kind — 'device' on TPU
    PJRT, 'unpinned_host' on the CPU backend, whose only memory space
    IS host memory. Offload round-trips must target this rather than a
    literal 'device', which the CPU backend rejects."""
    try:
        return jax.devices()[0].default_memory().kind
    except Exception:
        return "device"


def shard_model_stage3(model, mesh=None):
    """Parameter sharding (ZeRO-3): each param's dim-0 over the fsdp axis."""
    mesh = mesh or get_mesh()
    ax = _fsdp_axis(mesh)
    if ax is None:
        return model
    n = mesh.shape[ax]
    for p in model.parameters():
        spec = P(ax) if _shardable(p, n) else P()
        p._value = shard_value(p._value, spec, mesh)
        p.sharding_spec = spec
    return model


def shard_optimizer_state(optimizer, mesh=None, offload=False):
    """Stage-1/2: optimizer moments (and thus grad reductions) sharded.
    offload=True additionally places the moments in the host memory space
    (reference GroupShardedOptimizerStage2(offload=True) / CPUAdam): XLA
    then streams them through HBM only around the update."""
    mesh = mesh or get_mesh()
    ax = _fsdp_axis(mesh)
    if ax is None and not offload:
        return optimizer
    n = mesh.shape[ax] if ax is not None else 1
    mem_kind = host_memory_kind() if offload else None
    if offload and mem_kind is None:
        warnings.warn(
            "offload=True requested but this backend reports no host "
            "memory space (pinned_host); optimizer state stays in device "
            "memory", RuntimeWarning)
    orig_init = optimizer._init_state

    def sharded_init(p):
        state = orig_init(p)
        spec = P(ax) if (ax is not None and _shardable(p, n)) else P()
        if mem_kind is not None and mesh is not None:
            sh = NamedSharding(mesh, spec, memory_kind=mem_kind)
            return {k: jax.device_put(v, sh) for k, v in state.items()}
        if mem_kind is not None:
            dst = jax.sharding.SingleDeviceSharding(
                jax.devices()[0], memory_kind=mem_kind)
            return {k: jax.device_put(v, dst) for k, v in state.items()}
        return {k: shard_value(v, spec, mesh) for k, v in state.items()}
    # marker for outer wrappers (fleet's HybridParallelOptimizer): this
    # init already placed the state deliberately — don't re-place it
    sharded_init._zero_sharded = True
    optimizer._init_state = sharded_init

    if mem_kind is not None:
        # XLA refuses mixed memory spaces inside one computation, so the
        # jitted update runs on device copies: moments stream host→HBM
        # before the update and back after — the CPUAdam data motion,
        # with PJRT doing the DMA
        orig_build = optimizer._build_step_fn_for

        def build_offloaded(params):
            inner = orig_build(params)
            dev_kind = device_memory_kind()

            def to_dev(v):
                return jax.device_put(
                    v, v.sharding.with_memory_kind(dev_kind))

            def to_host(v):
                return jax.device_put(
                    v, v.sharding.with_memory_kind(mem_kind))

            def stepped(lr, step, pvals, gvals, svals):
                svals = [[to_dev(v) for v in st] for st in svals]
                new_p, new_s = inner(lr, step, pvals, gvals, svals)
                new_s = [[to_host(v) for v in st] for st in new_s]
                return new_p, new_s
            return stepped
        optimizer._build_step_fn_for = build_offloaded
    return optimizer


class GroupShardedStage2:
    """API-compat wrapper (reference group_sharded_stage2.py:46)."""

    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None, offload=False):
        # sync_buffers/buffer_max_size are the reference's hand-written
        # grad-bucket machinery; under GSPMD the compiler owns bucketing,
        # and buffers are replicated by construction in SPMD
        self._layer = layer
        self._optimizer = shard_optimizer_state(optimizer, offload=offload)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layer"], name)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    forward = __call__


class GroupShardedStage3(GroupShardedStage2):
    """reference group_sharded_stage3.py:59 — adds parameter sharding."""

    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pretrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        shard_model_stage3(layer)
        super().__init__(layer, optimizer, group, offload=offload)


class GroupShardedOptimizerStage2:
    """reference group_sharded_optimizer_stage2.py:53."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu",
                 pretrain_sync_models=True, dp_group=None, **kw):
        self._optim = shard_optimizer_state(optim, offload=offload)

    def __getattr__(self, name):
        return getattr(self.__dict__["_optim"], name)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """paddle.distributed.sharding.group_sharded_parallel analog
    (reference distributed/sharding/group_sharded.py)."""
    mesh = get_mesh()
    if mesh is None and jax.device_count() > 1:
        set_global_mesh(build_mesh({"fsdp": jax.device_count()}))
    if level in ("os", "os_g", "p_g_os"):
        optimizer = shard_optimizer_state(optimizer, offload=offload)
    if level == "p_g_os":
        shard_model_stage3(model)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from .. import framework_io
    sd = model.state_dict()
    framework_io.save(sd, output + ".pdmodel.state")
    if optimizer is not None:
        framework_io.save(optimizer.state_dict(), output + ".pdopt")
