"""Distributed environment bootstrap.

Reference analog: paddle.distributed.init_parallel_env
(python/paddle/distributed/parallel.py:915) + TCPStore rendezvous
(/root/reference/paddle/phi/core/distributed/store/tcp_store.h).

TPU-native: JAX is single-controller-per-host; multi-host jobs rendezvous
through the JAX coordination service (jax.distributed.initialize) instead of
a TCPStore — PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM-style env vars map onto
process_id/num_processes. Within one host, all local TPU chips belong to this
one process (no per-GPU process forking), so "rank" here is the *process*
(host) index, and per-chip parallelism is expressed with a Mesh.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def init_parallel_env(strategy=None):
    """Bootstraps multi-host JAX if the launch env asks for it; no-op single
    host. Safe to call multiple times."""
    global _initialized
    if _initialized:
        return
    n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                           os.environ.get("WORLD_SIZE", "1")))
    if n > 1:
        # IMPORTANT: do NOT touch jax.process_count()/devices() here — any
        # backend query initializes the runtime, after which distributed
        # init can no longer federate the processes
        rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                  os.environ.get("RANK", "0")))
        coord = os.environ.get(
            "PADDLE_MASTER",
            os.environ.get("MASTER_ADDR", "127.0.0.1") + ":" +
            os.environ.get("MASTER_PORT", "12355"))
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=n, process_id=rank)
        except RuntimeError as e:
            # jax 0.9: "distributed.initialize should only be called once."
            msg = str(e).lower()
            if "once" not in msg and "already" not in msg:
                raise
    _initialized = True


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized():
    return _initialized


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


class ParallelEnv:
    """reference: python/paddle/distributed/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]
