"""DistributedStrategy.

Reference analog: fleet.DistributedStrategy
(python/paddle/distributed/fleet/base/distributed_strategy.py:113, backed by
distributed_strategy.proto:324). Same switchboard surface, plain Python
instead of protobuf — the strategy resolves to mesh axes + jit options
rather than graph passes.
"""
from __future__ import annotations

import copy


class HybridConfig(dict):
    pass


class DistributedStrategy:
    def __init__(self):
        self._hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        self._user_hybrid_keys = set()
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "use_dynamic_loss_scaling": True,
            "custom_white_list": [], "custom_black_list": [],
            "use_pure_fp16": False, "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 8}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.heter_ccl_mode = False
        self.a_sync = False
        self.a_sync_configs = {}
        self.asp = False
        self.qat = False
        self.auto_search = False
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.without_graph_optimization = True

    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, cfg):
        """MERGE into the defaults (a partial dict keeps the rest), and
        remember which keys the user set explicitly — fleet.init only
        auto-fills dp when dp_degree was NOT explicit."""
        self._user_hybrid_keys.update(cfg)
        self._hybrid_configs.update(cfg)

    def _set_hybrid(self, **kwargs):
        self._user_hybrid_keys.update(kwargs)
        self._hybrid_configs.update(kwargs)

    @property
    def hybrid_parallel_order(self):
        return self.hybrid_configs.get("order")

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()
                  if not k.startswith("_")}
        return f"DistributedStrategy({fields})"

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        new.__dict__.update(copy.deepcopy(
            {k: v for k, v in self.__dict__.items()}, memo))
        return new
