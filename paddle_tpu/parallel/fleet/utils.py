"""fleet.utils (reference python/paddle/distributed/fleet/utils/ —
recompute + the fs clients + hybrid-parallel helpers)."""
from __future__ import annotations

import os
import shutil

from .recompute import recompute  # noqa: F401


class LocalFS:
    """reference utils/fs.py LocalFS — the file-system client the fleet
    checkpoint utilities use."""

    def ls_dir(self, path):
        dirs, files = [], []
        for e in os.scandir(path):
            (dirs if e.is_dir() else files).append(e.name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)


class HDFSClient:
    """reference utils/fs.py HDFSClient — requires a hadoop deployment;
    not available in this environment."""

    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(
            "HDFSClient needs a hadoop deployment; use LocalFS (or mount "
            "the remote store) in this environment")
