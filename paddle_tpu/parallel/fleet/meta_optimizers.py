"""fleet.meta_optimizers (reference
python/paddle/distributed/fleet/meta_optimizers/ — the strategy-driven
optimizer rewrites). The switchboard lives in fleet/strategy.py; the
gradient-merge rewrite is a real optimizer here, and the sharding/
recompute/amp rewrites act through distributed_optimizer (fleet.py)."""
from __future__ import annotations

from ...optimizer.gradient_merge import (  # noqa: F401
    GradientMergeOptimizer)
from ..sharding import (  # noqa: F401
    GroupShardedOptimizerStage2 as ShardingOptimizer)
