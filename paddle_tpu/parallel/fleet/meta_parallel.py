"""fleet.meta_parallel (reference
python/paddle/distributed/fleet/meta_parallel/ — the hybrid-parallel
layer library: parallel_layers/mp_layers.py Column/Row/VocabParallel,
random.py get_rng_state_tracker, pp_layers.py:56 LayerDesc /
SharedLayerDesc / :259 PipelineLayer).

TPU-native: the mp layers come from parallel.mp_layers (NamedSharding
over the 'mp' axis; GSPMD inserts the collectives). PipelineLayer keeps
the reference's description surface — the single controller owns ALL
stages, so forward composes every layer; stage placement happens through
parameter sharding specs, and the pipelined schedule itself runs in
parallel.pipeline (spmd_pipeline) when the fleet model wrapper drives a
pp mesh."""
from __future__ import annotations

from ...nn.layer import Layer
from ..mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..random import get_rng_state_tracker  # noqa: F401
from .recompute import recompute  # noqa: F401


class LayerDesc:
    """reference pp_layers.py:56 — deferred layer construction."""

    def __init__(self, layer_func, *inputs, **kwargs):
        if not (isinstance(layer_func, type)
                and issubclass(layer_func, Layer)):
            raise TypeError(
                "layer_func needs to be a Layer subclass (the class "
                f"itself, not an instance); got {layer_func!r}")
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """reference pp_layers.py:76 — a layer shared across stages (e.g.
    tied embeddings); single-controller SPMD holds ONE instance, so
    sharing is by construction."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """reference pp_layers.py:259 — builds the layer list from descs and
    runs them in order. num_stages/topology describe the intended pp
    split; seg_method='uniform' partitioning is recorded in
    `stage_of_layer` for schedulers that want it."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None, name=None,
                 **kwargs):
        super().__init__()
        self._descs = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self._shared = {}
        self.run_function = []
        for i, d in enumerate(self._descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                fwd = d.forward_func
                self.run_function.append(
                    (lambda x, _l=layer, _f=fwd:
                     _f(_l, x) if _f else _l(x)))
                self.add_sublayer(str(i), layer)
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.run_function.append(layer)
                self.add_sublayer(str(i), layer)
            elif isinstance(d, Layer):
                self.run_function.append(d)
                self.add_sublayer(str(i), d)
            elif callable(d):
                self.run_function.append(d)
            else:
                raise TypeError(f"unsupported pipeline entry {d!r}")
        n = len(self.run_function)
        per = max(1, n // self._num_stages)
        self.stage_of_layer = [min(i // per, self._num_stages - 1)
                               for i in range(n)]

    def get_num_stages(self):
        return self._num_stages

    def forward(self, x):
        for i, fn in enumerate(self.run_function):
            if (self._recompute_interval
                    and i % self._recompute_interval == 0
                    and isinstance(fn, Layer)):
                x = recompute(fn, x)
            else:
                x = fn(x)
        return x
