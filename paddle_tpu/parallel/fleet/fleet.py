"""The fleet facade.

Reference analog: python/paddle/distributed/fleet/fleet.py:167 (init),
fleet/model.py:30 (distributed_model), fleet.py:1057
(distributed_optimizer).

TPU-native: fleet.init builds the global Mesh from hybrid_configs (instead
of NCCL comm groups); distributed_model shards the model's parameters over
that mesh (dp/fsdp/mp axes) and returns a wrapper that applies sharding
constraints; distributed_optimizer shards optimizer state the same way
(ZeRO == state sharded along 'fsdp'/'dp'). Everything then runs through
GSPMD — one program, XLA inserts the collectives.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from ...framework.tensor import Tensor
from ..env import init_parallel_env, get_rank, get_world_size
from ..mesh import get_mesh, shard_value, sharding_for
from ..topology import (HybridCommunicateGroup, set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .strategy import DistributedStrategy


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self.compression: list = []      # dgc/localsgd/fp16_allreduce


_state = _FleetState()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """fleet.init (reference fleet.py:167). When the hybrid degrees don't
    account for every device, dp absorbs the remainder (the reference's
    topology does the same: dp = world // (mp·pp·sharding·sep))."""
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    dp = int(hc.get("dp_degree", 1))
    others = (int(hc.get("mp_degree", 1)) * int(hc.get("pp_degree", 1)) *
              int(hc.get("sharding_degree", 1)) *
              int(hc.get("sep_degree", 1)))
    import jax
    world = jax.device_count()
    dp_explicit = "dp_degree" in getattr(strategy, "_user_hybrid_keys",
                                         ())
    if not dp_explicit and dp * others < world and world % others == 0:
        dp = world // others
    hcg = HybridCommunicateGroup(
        dp_degree=dp,
        mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1))
    set_hybrid_communicate_group(hcg)
    _state.initialized = True
    _state.strategy = strategy
    _state.hcg = hcg
    return hcg


def is_initialized():
    return _state.initialized


def get_hybrid_communicate_group_():
    return _state.hcg


def _shard_model_params(model, mesh, zero3=False):
    """Place every parameter according to its sharding_spec (TP layers set
    one); default spec: replicated over dp/mp, FSDP-sharded along the
    ZeRO axis when the mesh has one. zero3 (strategy.sharding stage 3)
    lowers the size threshold to the group-sharded module's (>=1024),
    sharding everything shardable — TP specs always win."""
    from ..sharding import _fsdp_axis
    if zero3:
        # ZeRO-3 axis: 'fsdp' when the topology has one, else fall back
        # to 'dp' (users set the stage without a sharding_degree all the
        # time; the dp replicas then host the shards — reference
        # DygraphShardingOptimizer). Without stage 3, plain DP keeps
        # params replicated and only an explicit fsdp axis shards.
        ax = _fsdp_axis(mesh)
    else:
        ax = "fsdp" if ("fsdp" in mesh.axis_names and
                        mesh.shape["fsdp"] > 1) else None
    threshold = 1024 if zero3 else 4096
    for p in model.parameters():
        spec = p.sharding_spec
        if spec is None:
            if ax is not None and p.ndim >= 1 and \
                    p.shape[0] % mesh.shape[ax] == 0 and \
                    p.size >= threshold:
                spec = P(ax)
                p.sharding_spec = spec
            else:
                spec = P()
        p._value = shard_value(p._value, spec, mesh)
    for b in model.buffers():
        b._value = shard_value(b._value, P(), mesh)


class HybridParallelModelWrapper:
    """distributed_model return value: applies input sharding (dp on batch)
    and delegates; params already sharded. strategy.amp autocasts the
    forward; strategy.recompute routes it through the checkpointed
    StaticFunction path."""

    def __init__(self, model, hcg, strategy=None):
        self._layers = model
        self._hcg = hcg
        self._amp_cfg = None
        self._recompute = False
        if strategy is not None and getattr(strategy, "amp", False):
            c = getattr(strategy, "amp_configs", {}) or {}
            self._amp_cfg = {
                "dtype": "bfloat16" if c.get("use_bf16", True)
                else "float16",
                "level": "O2" if c.get("use_pure_fp16") else "O1",
                "white": c.get("custom_white_list") or None,
                "black": c.get("custom_black_list") or None,
            }
        if strategy is not None and getattr(strategy, "recompute", False):
            self._recompute = True

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        mesh = self._hcg.mesh
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        new_args = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim >= 1 and batch_axes:
                if a.shape[0] % int(np.prod([mesh.shape[x]
                                             for x in batch_axes])) == 0:
                    a = Tensor(shard_value(
                        a._value, P(batch_axes), mesh),
                        stop_gradient=a.stop_gradient)
            new_args.append(a)

        def call(*ca, **ck):
            if self._recompute:
                from .recompute import recompute
                return recompute(self._layers, *ca, **ck)
            return self._layers(*ca, **ck)

        if self._amp_cfg is not None:
            from ... import amp as _amp
            with _amp.auto_cast(enable=True, level=self._amp_cfg["level"],
                                dtype=self._amp_cfg["dtype"],
                                custom_white_list=self._amp_cfg["white"],
                                custom_black_list=self._amp_cfg["black"]):
                return call(*new_args, **kwargs)
        return call(*new_args, **kwargs)

    def forward(self, *args, **kwargs):
        return self(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """PipelineParallel.train_batch-shaped entry
        (reference meta_parallel/pipeline_parallel.py:312)."""
        from ...nn import functional as F
        inputs, labels = data
        loss = self._layers.compute_loss(inputs, labels) if hasattr(
            self._layers, "compute_loss") else None
        if loss is None:
            logits = self(inputs)
            loss = F.cross_entropy(logits, labels)
        if scaler is not None:
            scaler.scale(loss).backward()
            scaler.step(optimizer)
            scaler.update()
        else:
            loss.backward()
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


def distributed_model(model):
    """fleet.distributed_model (reference fleet/model.py:30). Honors
    strategy.sharding stage 3 (parameter sharding), strategy.amp and
    strategy.recompute via the wrapper."""
    if not _state.initialized:
        init()
    mesh = _state.hcg.mesh
    strategy = _state.strategy
    stage = 0
    if strategy is not None and getattr(strategy, "sharding", False):
        stage = int(getattr(strategy, "sharding_configs",
                            {}).get("stage", 1))
    # one placement mechanism: TP specs always win; stage 3 widens the
    # fsdp default to everything shardable
    _shard_model_params(model, mesh, zero3=stage >= 3)
    return HybridParallelModelWrapper(model, _state.hcg, strategy)


class HybridParallelOptimizer:
    """fleet.distributed_optimizer (reference
    hybrid_parallel_optimizer.py:238). Shards optimizer state along the
    fsdp axis (ZeRO-1/2) by initializing state with the parameter's
    sharding (XLA keeps moments distributed automatically)."""

    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._shard_states()

    def _shard_states(self):
        mesh = self._hcg.mesh
        # unwrap GradientMergeOptimizer etc.: the hook must land on the
        # object whose _init_state actually runs
        opt = getattr(self._inner_opt, "inner_opt", self._inner_opt)
        if getattr(opt._init_state, "_zero_sharded", False):
            # strategy.sharding already installed a deliberate placement
            # (ZeRO specs, possibly host-offloaded) — re-placing onto the
            # param's sharding would silently undo it
            return
        orig_init = opt._init_state

        def sharded_init(p):
            state = orig_init(p)
            sharding = getattr(p._value, "sharding", None)
            if sharding is not None:
                # don't clobber an inner placement that already decided a
                # memory space (strategy.sharding offload puts moments in
                # pinned_host — re-device_put here would silently pull
                # them back to HBM)
                state = {
                    k: (v if getattr(getattr(v, "sharding", None),
                                     "memory_kind", None)
                        not in (None, "device")
                        else jax.device_put(v, sharding))
                    for k, v in state.items()}
            return state
        opt._init_state = sharded_init

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, **kwargs):
        return self._inner_opt.minimize(loss, **kwargs)


def distributed_optimizer(optimizer, strategy=None):
    """Every consumed strategy toggle acts here; toggles whose mechanism
    has no TPU analog raise instead of silently doing nothing."""
    if not _state.initialized:
        init(strategy=strategy)
    if strategy is not None:
        # the reference treats the strategy handed to
        # distributed_optimizer as THE user strategy — distributed_model
        # called later must see the same toggles
        _state.strategy = strategy
    strategy = strategy or _state.strategy
    if strategy is not None:
        # Gradient-compression-class strategies (reference
        # meta_optimizers/{dgc,localsgd,fp16_allreduce}_optimizer.py):
        # pointless on an ICI slice (GSPMD's fused reduction outruns the
        # compression math) but real on DCN-crossing multi-slice DP.
        # The mechanisms live in parallel.compression; the toggle here
        # records the configuration for the explicit shard_map path
        # (multislice_grad_sync below) — the implicit GSPMD step has no
        # reduction site to rewrite, by design.
        wanted = [t for t in ("dgc", "localsgd", "fp16_allreduce")
                  if getattr(strategy, t, False)]
        if wanted:
            import warnings
            _state.compression = wanted
            warnings.warn(
                f"DistributedStrategy {wanted}: applied only on the "
                "explicit multi-slice path — call "
                "fleet.multislice_grad_sync(grads, ...) (or "
                "parallel.compression directly) inside shard_map over "
                "the slice axis; the single-slice GSPMD reduction is "
                "already fused+overlapped and is NOT rewritten.",
                stacklevel=2)
        if getattr(strategy, "lars", False):
            from ...optimizer import Lars, Momentum
            if isinstance(optimizer, Momentum):
                cfg = getattr(strategy, "lars_configs", None) or {}
                optimizer = Lars(
                    learning_rate=optimizer._learning_rate,
                    momentum=getattr(optimizer, "_momentum", 0.9),
                    lars_coeff=cfg.get("lars_coeff", 0.001),
                    lars_weight_decay=cfg.get(
                        "lars_weight_decay",
                        optimizer._weight_decay_coeff or 0.0005),
                    grad_clip=optimizer._grad_clip,
                    parameters=optimizer._parameter_list)
            elif not isinstance(optimizer, Lars):
                # reference LarsOptimizer meta-opt applies to Momentum
                # only; replacing Adam et al. would change the training
                # math behind the user's back
                import warnings
                warnings.warn(
                    f"strategy.lars applies to Momentum optimizers only "
                    f"(reference LarsOptimizer); "
                    f"{type(optimizer).__name__} left unchanged",
                    RuntimeWarning)
        if getattr(strategy, "lamb", False):
            from ...optimizer import Lamb
            if not isinstance(optimizer, Lamb):
                # carry the scheduler OBJECT and grad_clip over, not a
                # frozen float / nothing
                optimizer = Lamb(
                    learning_rate=optimizer._learning_rate,
                    lamb_weight_decay=(getattr(strategy, "lamb_configs",
                                               None) or
                                       {}).get("lamb_weight_decay", 0.01),
                    grad_clip=optimizer._grad_clip,
                    parameters=optimizer._parameter_list)
        if getattr(strategy, "sharding", False):
            from ..sharding import shard_optimizer_state
            cfg = getattr(strategy, "sharding_configs", {}) or {}
            optimizer = shard_optimizer_state(
                optimizer, offload=bool(cfg.get("offload", False)))
        if getattr(strategy, "gradient_merge", False):
            from ...optimizer.gradient_merge import GradientMergeOptimizer
            cfg = getattr(strategy, "gradient_merge_configs", {})
            optimizer = GradientMergeOptimizer(
                optimizer, k_steps=int(cfg.get("k_steps", 1)),
                avg=bool(cfg.get("avg", True)))
        if getattr(strategy, "asp", False):
            from ...incubate import asp as _asp
            optimizer = _asp.decorate(optimizer)
    return HybridParallelOptimizer(optimizer, _state.hcg, strategy)


# ------- worker-info surface (reference fleet.py worker_num etc.) -------
def multislice_grad_sync(grads, axis_name: str = "slice",
                         residuals=None, strategy=None):
    """Cross-slice gradient reduction honoring the configured
    compression strategy (reference meta_optimizers dgc/fp16_allreduce,
    applied where they actually pay off: an explicit shard_map reduction
    over a DCN-crossing 'slice' axis — see parallel.compression).

    grads: pytree. Returns (synced_grads, residuals): residuals is the
    DGC error-feedback state (zeros-like on first call, thread it
    through every step); None when the strategy doesn't use DGC.
    k_frac for DGC comes from strategy.dgc_configs['sparsity'] (the
    reference's [0.999] spelling → keep 0.1%).
    """
    import jax as _jax
    from ..compression import compressed_psum, dgc_psum
    strategy = strategy or _state.strategy
    tree = _jax.tree_util
    if strategy is not None and getattr(strategy, "dgc", False):
        cfgs = getattr(strategy, "dgc_configs", None) or {}
        sparsity = cfgs.get("sparsity", [0.999])
        sparsity = sparsity[0] if isinstance(
            sparsity, (list, tuple)) else sparsity
        k_frac = max(1e-6, 1.0 - float(sparsity))
        if residuals is None:
            residuals = tree.tree_map(
                lambda g: _jax.numpy.zeros_like(g), grads)
        pairs = tree.tree_map(
            lambda g, r: dgc_psum(g, r, axis_name, k_frac=k_frac),
            grads, residuals)
        # structural unzip: `pairs` has the grads tree's structure with a
        # (synced, residual) 2-tuple at every LEAF position. A tuple
        # is_leaf sniff would misfire when the grads pytree itself
        # contains tuples (e.g. the tuple jax.grad(..., argnums=(0, 1))
        # returns) and silently hand one leaf's residual out as another
        # leaf's gradient; tree_transpose flips outer/inner by structure
        # instead, so container tuples are never mistaken for pairs.
        synced, new_res = tree.tree_transpose(
            tree.tree_structure(grads), tree.tree_structure((0, 0)),
            pairs)
        return synced, new_res
    if strategy is not None and getattr(strategy, "fp16_allreduce",
                                        False):
        return tree.tree_map(
            lambda g: compressed_psum(g, axis_name), grads), None
    return tree.tree_map(
        lambda g: _jax.lax.psum(g, axis_name), grads), None


def worker_num():
    return get_world_size()


def worker_index():
    return get_rank()


def is_first_worker():
    return get_rank() == 0

def is_worker():
    return True


def is_server():
    return False


def barrier_worker():
    import jax.numpy as jnp
    jax.block_until_ready(jnp.zeros(()))


def stop_worker():
    pass
