"""Activation recomputation (gradient checkpointing).

Reference analog: paddle.distributed.fleet.recompute
(fleet/recompute/recompute.py:332, PyLayer-based re-forward in backward)
and recompute_hybrid.py.

TPU-native: the region becomes ONE fused op (via the to_static capture
machinery) whose pure function is wrapped in jax.checkpoint — XLA
rematerializes the region's activations in backward. The tape then stores
only the region's *inputs* instead of every intermediate op's saved
tensors, which is the memory win the reference gets from PyLayer.
"""
from __future__ import annotations

from ...jit.static_function import StaticFunction

_recompute_cache = {}


def recompute(function, *args, **kwargs):
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    offload_indices = kwargs.pop("offload_indices", None)
    fn = function.forward if hasattr(function, "forward") and not callable(
        function) else function
    key = id(getattr(fn, "__func__", fn))
    sf = _recompute_cache.get(key)
    if sf is None:
        sf = StaticFunction(fn if not hasattr(fn, "forward") else fn.forward,
                            remat=True)
        if hasattr(function, "training"):
            sf._layer = function
        _recompute_cache[key] = sf
    return sf(*args, **kwargs)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference: recompute_sequential — checkpoint each segment of a
    Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions) if not hasattr(functions, "_sub_layers") else \
        list(functions._sub_layers.values())
    n = len(layers)
    per = max(1, n // segments)
    x = args[0] if args else kwargs.pop("x")
    i = 0
    while i < n:
        seg = layers[i:i + per]

        def seg_fn(inp, _seg=tuple(seg)):
            for l in _seg:
                inp = l(inp)
            return inp
        x = recompute(seg_fn, x)
        i += per
    return x


def recompute_hybrid(ctx, function, *args, **kwargs):
    return recompute(function, *args, **kwargs)
