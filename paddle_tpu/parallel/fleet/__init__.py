"""paddle_tpu.parallel.fleet (reference: python/paddle/distributed/fleet/)."""
from .strategy import DistributedStrategy  # noqa: F401
from .fleet import (  # noqa: F401
    init, is_initialized, distributed_model, distributed_optimizer,
    HybridParallelOptimizer, multislice_grad_sync, worker_num,
    worker_index, is_first_worker, is_worker, is_server, barrier_worker,
    stop_worker)
from ..topology import get_hybrid_communicate_group  # noqa: F401
from ..random import get_rng_state_tracker  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401


class UtilBase:
    def all_reduce(self, input, mode="sum"):  # noqa: A002
        return input

    def barrier(self):
        from .fleet import barrier_worker
        barrier_worker()


util = UtilBase()


# ------------------------------------------------------------ fleet tail
# (reference distributed/fleet/__init__.py __all__: Fleet class, role
# makers, topology classes, PS data generators)
from ..topology import (  # noqa: E402,F401
    CommunicateTopology, HybridCommunicateGroup)
from . import fleet as _fleet_mod


class Role:
    """reference base/role_maker.py:31."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """reference base/role_maker.py:547 — resolves the process's role
    from the cluster environment. Collective TPU training has workers
    only; rank/size come from the same PADDLE_* env contract
    parallel.env reads."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self):
        from ..env import get_rank
        return get_rank()

    def _worker_num(self):
        from ..env import get_world_size
        return get_world_size()

    def _role(self):
        return Role.WORKER

    def _is_worker(self):
        return True

    def _is_server(self):
        return False

    def _is_first_worker(self):
        return self._worker_index() == 0


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference base/role_maker.py:1183 — explicit role assignment."""

    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._kwargs = kwargs


class MultiSlotDataGenerator:
    """reference data_generator — emits the PS text format
    'slot:count id id ...' per sample; subclass generate_sample."""

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) -> iterator of "
            "[(slot_name, [ids...]), ...]")

    def _format(self, record):
        parts = []
        for _name, ids in record:
            parts.append(str(len(ids)))
            parts.extend(str(i) for i in ids)
        return " ".join(parts)

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            for rec in self.generate_sample(line)():
                sys.stdout.write(self._format(rec) + "\n")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            for rec in self.generate_sample(line)():
                out.append(self._format(rec))
        return out


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """reference — string-id variant (same line format, ids kept as
    strings)."""


class Fleet:
    """reference fleet/fleet.py:99 — the unified distributed-training
    facade as a class; the module-level `fleet` object in the reference
    is an instance of this. Methods delegate to the functional core."""

    def __init__(self):
        self._role_maker = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        return _fleet_mod.init(role_maker=role_maker,
                               is_collective=is_collective,
                               strategy=strategy, log_level=log_level)

    def __getattr__(self, name):
        # every other fleet API (distributed_model/optimizer/worker_num/
        # barrier_worker/...) lives in the functional module
        return getattr(_fleet_mod, name)
