"""paddle_tpu.parallel.fleet (reference: python/paddle/distributed/fleet/)."""
from .strategy import DistributedStrategy  # noqa: F401
from .fleet import (  # noqa: F401
    init, is_initialized, distributed_model, distributed_optimizer,
    HybridParallelOptimizer, worker_num, worker_index, is_first_worker,
    is_worker, is_server, barrier_worker, stop_worker)
from ..topology import get_hybrid_communicate_group  # noqa: F401
from ..random import get_rng_state_tracker  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401


class UtilBase:
    def all_reduce(self, input, mode="sum"):  # noqa: A002
        return input

    def barrier(self):
        from .fleet import barrier_worker
        barrier_worker()


util = UtilBase()
