"""Mixture-of-Experts: gates, capacity-based dispatch, expert parallelism.

Reference analog: the incubate MoE stack —
/root/reference/python/paddle/incubate/distributed/models/moe/moe_layer.py:261
(MoELayer over global_scatter/global_gather NCCL all-to-all) and the gate zoo
moe/gate/{naive,switch,gshard}_gate.py.

TPU-native redesign: the GShard dense-dispatch formulation. Routing builds
one-hot dispatch/combine tensors [T, E, C] (C = capacity); token->expert
transport is the einsum contraction 'td,tec->ecd' whose expert axis is
sharded over the 'ep' mesh axis — XLA GSPMD lowers the contraction to the
ICI all-to-all that the reference performs with NCCL global_scatter. No
host-driven routing, fully jit/vjp compatible, static shapes (dropped
tokens beyond capacity contribute zero, exactly like the reference's
capacity overflow).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import get_mesh, constraint as mesh_constraint


def compute_capacity(num_tokens: int, num_experts: int,
                     capacity_factor: float, min_capacity: int = 4) -> int:
    """Per-expert token slots (reference switch/gshard capacity rule)."""
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def topk_gating(probs, k: int, capacity: int, normalize: bool = None):
    """GShard top-k routing with per-expert capacity.

    probs: [T, E] softmax gate probabilities.
    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss scalar). Tokens assigned past an expert's capacity are
    dropped (their dispatch/combine rows are zero).

    normalize: renormalize combine weights over the token's KEPT choices.
    Default: True for k>1 (GShard top-2 semantics), False for k=1 —
    Switch-Transformer scales the expert output by the RAW gate
    probability so the router receives gradient through the task loss.
    """
    if normalize is None:
        normalize = k > 1
    T, E = probs.shape
    remaining = probs
    prior_count = jnp.zeros((E,), probs.dtype)
    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    gate_kept = jnp.zeros((T,), probs.dtype)
    combine = jnp.zeros((T, E, capacity), probs.dtype)

    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                   # [T]
        mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)       # [T, E]
        pos = jnp.cumsum(mask, axis=0) - 1.0 + prior_count[None, :]
        pos_tok = jnp.sum(pos * mask, axis=-1)                 # [T]
        keep = (pos_tok < capacity).astype(probs.dtype)        # [T]
        kept_mask = mask * keep[:, None]
        prior_count = prior_count + jnp.sum(kept_mask, axis=0)
        gate_val = jnp.sum(probs * kept_mask, axis=-1)         # [T]
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                              dtype=probs.dtype)               # [T, C]
        d = kept_mask[:, :, None] * slot[:, None, :]           # [T, E, C]
        dispatch = dispatch + d
        combine = combine + gate_val[:, None, None] * d
        gate_kept = gate_kept + gate_val
        remaining = remaining * (1.0 - mask)

    if normalize:
        denom = jnp.maximum(gate_kept, 1e-9)
        combine = combine / denom[:, None, None]

    # load-balancing aux loss (switch eq. 4 / gshard): E * <f_e * p_e>
    me = jnp.mean(probs, axis=0)                               # mean prob
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=probs.dtype)
    ce = jnp.mean(top1, axis=0)                                # token frac
    aux_loss = E * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


@dataclasses.dataclass
class GateSpec:
    """Gate zoo entry (reference moe/gate/*.py)."""
    name: str
    top_k: int
    use_capacity: bool


GATES = {
    "naive": GateSpec("naive", 1, False),    # dense masked, no drops
    "switch": GateSpec("switch", 1, True),   # top-1 + capacity
    "gshard": GateSpec("gshard", 2, True),   # top-2 + capacity
}


def moe_ffn(x, gate_w, up_w, up_b, down_w, down_b, *,
            gate: str = "switch", capacity_factor: float = 1.25,
            ep_axis: str = "ep"):
    """Expert-parallel MoE FFN on [B, S, D] activations.

    gate_w [D, E]; up_w [E, D, F]; up_b [E, F]; down_w [E, F, D];
    down_b [E, D]. Expert (E) dims sharded on `ep_axis` make GSPMD lower
    the dispatch einsums to all-to-all over ICI.
    Returns (y [B, S, D], aux_loss scalar).
    """
    B, S, D = x.shape
    E = gate_w.shape[-1]
    spec = GATES[gate]
    T = B * S
    xt = x.reshape(T, D)

    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, -1)

    if not spec.use_capacity:
        # dense masked form (naive gate): every expert sees every token
        top1 = jnp.argmax(probs, -1)
        onehot = jax.nn.one_hot(top1, E, dtype=x.dtype)
        gate_val = jnp.take_along_axis(
            probs, top1[:, None], -1)[:, 0].astype(x.dtype)
        xe = jnp.einsum("td,te->etd", xt, onehot)
        h = jax.nn.gelu(jnp.einsum("etd,edf->etf", xe,
                                   up_w.astype(x.dtype))
                        + up_b[:, None, :].astype(x.dtype))
        ye = jnp.einsum("etf,efd->etd", h, down_w.astype(x.dtype)) \
            + down_b[:, None, :].astype(x.dtype)
        y = jnp.einsum("etd,te->td", ye, onehot) * gate_val[:, None]
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=probs.dtype), axis=0)
        aux = E * jnp.sum(me * ce)
        return y.reshape(B, S, D), aux.astype(jnp.float32)

    C = compute_capacity(T, E, capacity_factor)
    dispatch, combine, aux = topk_gating(probs, spec.top_k, C)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    # token -> expert transport: [T,D] x [T,E,C] -> [E,C,D] (the GSPMD
    # all-to-all when E is ep-sharded and T is dp-sharded)
    xe = jnp.einsum("td,tec->ecd", xt, dispatch)
    xe = mesh_constraint(xe, P(ep_axis, None, None))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, up_w.astype(x.dtype))
                    + up_b[:, None, :].astype(x.dtype))
    ye = jnp.einsum("ecf,efd->ecd", h, down_w.astype(x.dtype)) \
        + down_b[:, None, :].astype(x.dtype)
    ye = mesh_constraint(ye, P(ep_axis, None, None))
    y = jnp.einsum("ecd,tec->td", ye, combine)
    return y.reshape(B, S, D), aux.astype(jnp.float32)


class MoELayer:
    """nn-level MoE layer (reference MoELayer, moe_layer.py:261).

    Single-controller: holds the gate + stacked expert weights; experts'
    leading axis is sharded on the 'ep' mesh axis when a mesh is active.
    forward(x [B,S,D]) -> [B,S,D]; the last aux (load-balancing) loss is
    available as .aux_loss — add `layer.aux_loss * coeff` to the train
    loss like the reference's gate loss.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: str = "switch", capacity_factor: float = 1.25,
                 seed: int = 0, dtype=jnp.float32):
        from ..nn.parameter import Parameter
        if gate not in GATES:
            raise ValueError(f"unknown gate {gate!r}; options: "
                             f"{sorted(GATES)}")
        self.gate = gate
        self.capacity_factor = float(capacity_factor)
        self.num_experts = num_experts
        k = jax.random.split(jax.random.PRNGKey(seed), 4)
        E, D, F = num_experts, d_model, d_hidden
        std = 0.02

        def norm(key, shape, scale=std):
            return (jax.random.normal(key, shape, jnp.float32) *
                    scale).astype(dtype)

        from .mesh import shard_value
        specs = {
            "gate_w": P(None, None),
            "up_w": P("ep", None, None),
            "up_b": P("ep", None),
            "down_w": P("ep", None, None),
            "down_b": P("ep", None),
        }
        raw = {
            "gate_w": norm(k[0], (D, E)),
            "up_w": norm(k[1], (E, D, F)),
            "up_b": jnp.zeros((E, F), dtype),
            "down_w": norm(k[2], (E, F, D)),
            "down_b": jnp.zeros((E, D), dtype),
        }
        mesh = get_mesh()
        if mesh is not None and "ep" in mesh.axis_names:
            raw = {n: shard_value(v, specs[n], mesh)
                   for n, v in raw.items()}
        self._params = {n: Parameter(v, name=f"moe.{n}")
                        for n, v in raw.items()}
        self.aux_loss = None
        self.training = True

    def parameters(self):
        return list(self._params.values())

    def named_parameters(self, *a, **k):
        return list(self._params.items())

    def forward(self, x):
        from ..framework.dispatch import apply
        names = list(self._params)

        def _fwd(xv, *pvals, _gate=None, _cap=None):
            p = dict(zip(names, pvals))
            y, aux = moe_ffn(xv, p["gate_w"], p["up_w"], p["up_b"],
                             p["down_w"], p["down_b"], gate=_gate,
                             capacity_factor=_cap)
            return y, aux

        y, aux = apply("moe_layer", _fwd, x,
                       *[self._params[n] for n in names],
                       _gate=self.gate, _cap=self.capacity_factor)
        self.aux_loss = aux
        return y

    __call__ = forward
