"""Host-driven pipeline parallelism: per-stage compiled fns + 1F1B loop.

Reference analog: the FleetExecutor/PipelineParallel host schedule —
1F1B and its interleaved virtual-stage variant
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:188,
565) issuing per-stage programs with P2P activation exchange
(pp_utils/p2p_communication.py:733).

TPU-native translation (single-controller): each chunk of layers is a
separately-jitted function whose parameters live on one device of the
'pp' axis; the host loop issues forward/backward calls in 1F1B order and
JAX's async dispatch + per-device FIFO queues realize the overlap — a
transfer becomes the data dependence that used to be a NCCL P2P, and the
device starts a microbatch the moment its input lands. The backward
recomputes the stage forward (jax.vjp inside the jitted bwd), which is
the reference's recompute-in-1F1B memory behavior.

This is the multi-executable alternative to parallel.pipeline's
single-program SPMD formulation. Trade-offs, measured in
tools/ab_pipeline.py (results in perf/pipeline_ab.json):
- the SPMD scan is one XLA program — no per-call dispatch cost, works
  inside jit/grad, and is the only sane choice over a high-latency link
  (the axon tunnel pays ~100 ms PER DISPATCH, and this path issues
  O(m * v * p) of them);
- the host loop supports TRUE interleaved virtual stages: a microbatch
  makes v shorter hops around the ring, so warmup shrinks and the bubble
  is ~(p-1)/(v*m) instead of the scan formulation's (v*p-1)/(m+v*p-1),
  which strictly worsens with v. Interleave>1 therefore lives HERE, not
  in spmd_pipeline.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp

from .mesh import get_mesh

__all__ = ["HostPipeline", "stage_devices"]


def stage_devices(mesh=None, axis: str = "pp"):
    """One representative device per pp rank (the first along every other
    mesh axis)."""
    import numpy as np
    mesh = mesh or get_mesh()
    idx = mesh.axis_names.index(axis)
    arr = np.moveaxis(mesh.devices, idx, 0)
    # arr[i] is a bare Device for a 1-D (pure-pp) mesh; ravel handles both
    return [np.ravel(arr[i])[0] for i in range(arr.shape[0])]


class HostPipeline:
    """Build-once host-scheduled pipeline; call `grads` per step.

    stage_fn(chunk_params, x) -> y. Chunk c's parameters are placed on
    pp device c % n_stages, so interleave>1 round-robins chunks exactly
    like the reference's virtual stages. The per-stage executables are
    created once here and reused every step (jax.jit caches on the
    committed device: p forward + p backward compiles total).

    Scope: pure-pp, single-controller-local. Each stage runs on ONE
    device (the first along every other mesh axis) — on a hybrid
    dp x pp x mp mesh the other axes sit idle here; hybrid topologies
    pipeline through parallel.pipeline's SPMD formulation, which keeps
    dp/mp under GSPMD inside each stage.
    """

    def __init__(self, stage_fn: Callable, loss_fn: Callable,
                 n_stages: int, n_microbatches: int, interleave: int = 1,
                 mesh=None):
        self.p = n_stages
        self.v = interleave
        self.m = n_microbatches
        self.n_chunks = n_stages * interleave
        self.devs = stage_devices(mesh, "pp")

        @jax.jit
        def fwd(params, x):
            # x is NOT donated: the same buffer is held in `acts` until
            # this microbatch's backward replays the stage
            return stage_fn(params, x)

        # dy is consumed at its only use, so its buffer is donated and
        # dx aliases it (same shape/dtype for equal-width stages) —
        # one fewer activation-sized live buffer per in-flight backward.
        # x is NOT donated even though acts has popped it: for chunk 0
        # the device_put in issue_fwd is a no-op when the microbatch
        # already lives on stage 0, so the saved activation IS the
        # caller's input buffer and donating it would invalidate x_mb
        # between steps. params stay undonated (reused every microbatch).
        @functools.partial(jax.jit, donate_argnums=(2,))
        def bwd(params, x, dy):
            # recompute-in-backward: vjp replays the stage forward
            _, pull = jax.vjp(stage_fn, params, x)
            return pull(dy)

        # y (the last stage's output) is consumed here; dy aliases it
        @functools.partial(jax.jit, donate_argnums=(0,))
        def loss_and_grad(y):
            return jax.value_and_grad(loss_fn)(y)

        self._fwd, self._bwd, self._lg = fwd, bwd, loss_and_grad

    def place(self, stacked_params) -> List:
        """Split the stacked (leading dim = n_chunks, natural order)
        param pytree into per-chunk trees pinned to their stage device.
        Accepts any pytree, like pipeline_forward does."""
        leaves, _ = jax.tree_util.tree_flatten(stacked_params)
        for a in leaves:
            if a.shape[0] != self.n_chunks:
                raise ValueError(
                    f"a param leaf has leading dim {a.shape[0]}, "
                    f"expected n_stages*interleave={self.n_chunks}")
        return [jax.tree_util.tree_map(
                    lambda a: jax.device_put(a[c], self.devs[c % self.p]),
                    stacked_params)
                for c in range(self.n_chunks)]

    def grads(self, chunk_params: List[Dict], x_mb):
        """One 1F1B step -> (mean microbatch loss, per-chunk grad list).

        Host-level 1F1B: tick t injects microbatch t's forward chain
        and, once the pipeline is full, drains microbatch t-(p-1)'s
        backward chain. Issue order is the schedule; per-device FIFO
        queues overlap the execution. Activations are held per
        (microbatch, chunk) until their backward consumes them — the
        host-side analog of the reference's p2p buffer bookkeeping.
        """
        p, m, n_chunks = self.p, self.m, self.n_chunks
        acts: Dict = {}
        losses = []
        grads: List = [None] * n_chunks

        def issue_fwd(i):
            x = x_mb[i]
            for c in range(n_chunks):
                # the P2P hop: an async device_put onto the next stage's
                # device is the transfer the reference does over NCCL
                x = jax.device_put(x, self.devs[c % p])
                acts[(i, c)] = x
                x = self._fwd(chunk_params[c], x)
            return x

        def issue_bwd(i, y):
            lval, dy = self._lg(y)
            losses.append(lval)
            for c in reversed(range(n_chunks)):
                dy = jax.device_put(dy, self.devs[c % p])
                x = acts.pop((i, c))
                dparams, dy = self._bwd(chunk_params[c], x, dy)
                grads[c] = dparams if grads[c] is None else \
                    jax.tree_util.tree_map(jnp.add, grads[c], dparams)

        outs: Dict[int, jax.Array] = {}
        for t in range(m + p - 1):
            if t < m:
                outs[t] = issue_fwd(t)
            done = t - (p - 1)
            if done >= 0:
                issue_bwd(done, outs.pop(done))

        loss = jnp.mean(jnp.stack([jax.device_put(l, self.devs[0])
                                   for l in losses]))
        inv_m = 1.0 / m
        grads = [jax.tree_util.tree_map(lambda g: g * inv_m, g)
                 for g in grads]
        return loss, grads

    def gather_stacked(self, grads: List):
        """Per-chunk grad list -> stacked host-side arrays in natural
        chunk order (for parity checks / host optimizers). Accepts any
        pytree, mirroring place()."""
        import numpy as np
        return jax.tree_util.tree_map(
            lambda *leaves: np.stack(
                [np.asarray(jax.device_get(l)) for l in leaves]),
            *grads)
