"""Executable 4D pipeline-parallel training: dp×fsdp×tp×pp in ONE
full-manual shard_map.

Reference analog: the 1F1B pipeline schedule + hybrid-parallel engine
(fleet/meta_parallel/pipeline_parallel.py:188 — the 1F1B loop,
pp_layers.py:887 stage segmentation, and mp_layers.py:35,173's
ColumnParallel/RowParallel split), which runs per-rank processes
exchanging NCCL P2P tensors under a host-driven schedule. TPU-native
collapse: the whole dp×fsdp×tp×pp step is one SPMD program — stage
parameters are the stacked layer axis sharded over the 'pp' mesh axis
(planner.TrainPlan keeps 'pp' in the remapped specs), microbatches
circulate between neighbouring stages on parallel.pipeline's
scan-of-ppermute schedule, and the backward is jax autodiff replaying
that schedule in reverse (the 1F1B-shaped cooldown/warmup swap), so
the steady-state bubble is (pp-1)/(m+pp-1) per phase — the planner's
(pp-1)/m model, not the (pp-1)× serial fill of layer-sharded
execution.

Why FULL-manual: the partial-auto formulation (pp manual, dp/fsdp/tp
left to GSPMD — parallel/pipeline.pipeline_forward) fatally aborts
this container's legacy XLA partitioner
(utils.compat.spmd_pipeline_supported), so every axis here is
hand-partitioned inside one shard_map over the WHOLE mesh:

- tp: Megatron column/row-parallel — qkv/up matmuls consume this
  rank's column shard (heads/ffn columns), row-parallel outputs
  partial-sum then psum over 'tp'; the embedding and the tied LM head
  are vocab-parallel with a psum'd fused-CE (the lse and target-gather
  reductions cross the vocab shards);
- fsdp: ZeRO-3 — each weight's fsdp-sharded dim is all-gathered just
  in time inside the per-layer scan body (re-gathered in the backward
  under remat); the all_gather transpose IS the gradient
  reduce-scatter, so ZeRO-3's schedule falls out of autodiff;
- dp: pure batch replication — gradient psum after the backward;
- pp: the stage-chunk axis — each rank holds layers
  [s·L/pp, (s+1)·L/pp) of every stacked leaf and runs
  parallel.pipeline.spmd_pipeline's circulate schedule over the
  microbatched activations.

Gradient correctness under legacy shard_map (check_rep=False, where
psum transposes to psum): the differentiated scalar is the per-device
PARTIAL loss — CE masked to the LAST pipeline stage and divided by
dp·fsdp·tp — so the per-device contributions sum to the global loss
exactly once and the collective transposes compose to the exact
adjoint (validated to ~1e-7 relative against the unsharded grads).
After the backward, each gradient leaf is psum'd over exactly the
mesh axes its PartitionSpec does NOT name: axes the leaf is sharded
over already carry complete shard-gradients (the gather transposes
summed them), axes it is replicated over hold per-rank partials.

The step honors the facade contract `(params, opt_state, batch) ->
(loss, new_params, new_opt)` (plus a trailing bubble-fraction scalar
under with_stats=True — models.facade._PipelineTrainStep strips it and
publishes `train.bubble_fraction`), so donation, the resilient guard
and the telemetry accumulator ride it unchanged through
models.facade.make_train_step's pinned-sharding machinery.
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import _clean_spec, leaf_path_name as _leaf_name
from .pipeline import spmd_pipeline
from ..utils.compat import shard_map

__all__ = ["make_pp_step_fn"]


# ---------------------------------------------------------------- helpers
def _spec_axes(spec) -> set:
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            axes.add(a)
    return axes


def _gather(w, axis_name: str, axis: int):
    """Just-in-time ZeRO-3/tp weight gather (tiled along `axis`); the
    autodiff transpose is the gradient reduce-scatter."""
    return jax.lax.all_gather(w, axis_name, axis=axis, tiled=True)


def _vocab_parallel_embed(wte, tokens, tp_axis: str):
    """Embedding gather over a vocab-sharded [V/tp, D] table: local
    rows masked-gathered, psum over tp rebuilds the full rows (the
    transpose scatters the full cotangent back into each rank's
    shard)."""
    ti = jax.lax.axis_index(tp_axis)
    v_loc = wte.shape[0]
    idx = tokens.astype(jnp.int32) - ti * v_loc
    ok = (idx >= 0) & (idx < v_loc)
    x = jnp.take(wte, jnp.clip(idx, 0, v_loc - 1), axis=0)
    return jax.lax.psum(
        jnp.where(ok[..., None], x, jnp.zeros((), x.dtype)), tp_axis)


def _vocab_parallel_ce(logits, targets, tp_axis: str):
    """models/losses.fused_softmax_ce over vocab-sharded logits
    [.., V/tp]: the logsumexp and the target gather each cross the
    vocab shards with one psum; the global max rides a (stop-gradient)
    all_gather because legacy jax has no pmax differentiation rule —
    subtracting a constant leaves the math exact either way. Returns
    the mean loss over all positions."""
    lf = logits.astype(jnp.float32)
    ti = jax.lax.axis_index(tp_axis)
    v_loc = lf.shape[-1]
    mx = jax.lax.stop_gradient(jnp.max(
        jax.lax.all_gather(jnp.max(lf, -1), tp_axis, axis=0), axis=0))
    se = jax.lax.psum(jnp.sum(jnp.exp(lf - mx[..., None]), -1), tp_axis)
    lse = mx + jnp.log(se)
    tl = targets.astype(jnp.int32) - ti * v_loc
    ok = (tl >= 0) & (tl < v_loc)
    g = jnp.take_along_axis(lf, jnp.clip(tl, 0, v_loc - 1)[..., None],
                            -1)[..., 0]
    tgt = jax.lax.psum(jnp.where(ok, g, jnp.zeros((), g.dtype)), tp_axis)
    return jnp.mean(lse - tgt)


def _run_pipeline(stacked, x, gather_fn, compute_fn, pp: int,
                  microbatches: int, remat: bool, overlap: bool = False):
    """Microbatch the local activations and run the stage-chunk scan
    through spmd_pipeline's circulate schedule. `stacked` leaves carry
    this rank's [L/pp, ...] stage chunk; returns (y, schedule stats).

    The per-layer block is split at the ZeRO-3 seam:
    `gather_fn(lp) -> gw` issues the just-in-time weight all-gathers,
    `compute_fn(gw, h)` is everything else. overlap=False composes the
    two inside the scan body — the historical trace, gather and compute
    strictly serial per layer. overlap=True double-buffers the gather
    through the scan CARRY: layer 0's weights gather before the scan,
    and iteration i issues layer i+1's all-gather BEFORE running layer
    i's compute, so XLA's async scheduler can slide the gather under
    the matmuls (latency-hiding collectives —
    docs/parallel_training.md §Collective overlap). The autodiff
    transpose replays the same offset in reverse: layer i+1's gradient
    reduce-scatter (the gather's transpose) lands in iteration i's
    backward, overlapping layer i's dgrad matmuls.

    Costs, by construction: one extra (discarded) gather per stage scan
    (the xs roll wraps layer 0 back in at the end), and — under
    remat — the gathered weights ride the carry, so they are saved as
    per-iteration residuals instead of re-gathered in the backward:
    overlap trades the ZeRO-3 backward re-gather's memory saving for
    schedule slack. That is why the knob is off by default."""
    if not overlap:
        def block_fn(lp, h):
            return compute_fn(gather_fn(lp), h)
        body = jax.checkpoint(block_fn) if remat else block_fn

        def stage_fn(chunk, h):
            def scan_body(h, lp):
                return body(lp, h), None
            h, _ = jax.lax.scan(scan_body, h, chunk)
            return h
    else:
        comp = jax.checkpoint(compute_fn) if remat else compute_fn

        def stage_fn(chunk, h):
            first = jax.tree_util.tree_map(lambda a: a[0], chunk)
            gw0 = gather_fn(first)
            # xs rolled by -1: iteration i carries layer i's gathered
            # weights in and sees layer i+1's SHARDED leaves as xs
            nxt = jax.tree_util.tree_map(
                lambda a: jnp.roll(a, -1, axis=0), chunk)

            def scan_body(carry, lp_next):
                h, gw = carry
                gw_next = gather_fn(lp_next)   # prefetch: issue first,
                h = comp(gw, h)                # compute hides it
                return (h, gw_next), None
            (h, _), _ = jax.lax.scan(scan_body, (h, gw0), nxt)
            return h

    b_loc = x.shape[0]
    x_mb = x.reshape((microbatches, b_loc // microbatches) + x.shape[1:])
    piped = spmd_pipeline(stage_fn, pp, microbatches,
                          schedule_stats=True)
    # spmd_pipeline expects the per-rank chunk behind a leading dim of 1
    # (pipeline_forward's P('pp') slicing); the raw [L/pp, ...] shard is
    # exactly that chunk
    chunk = jax.tree_util.tree_map(lambda a: a[None], stacked)
    y_mb, stats = piped(chunk, x_mb)
    return y_mb.reshape(x.shape), stats


# ------------------------------------------------------- family: GPT
def _gpt_gather_weights(lp, tp_axis: str):
    """The layer's just-in-time ZeRO-3/tp weight gathers — the overlap
    seam (_run_pipeline): everything here may be issued one layer ahead
    of the compute consuming it. Pass-through leaves (ln scales/biases,
    the tp-partial output biases) copy through unchanged so compute
    reads ONE dict."""
    gw = dict(lp)
    gw["qkv_w"] = _gather(_gather(lp["qkv_w"], "fsdp", 0),
                          tp_axis, 1)                          # [D, 3D]
    if lp.get("qkv_b") is not None:
        gw["qkv_b"] = _gather(lp["qkv_b"], tp_axis, 0)         # [3D]
    gw["attn_out_w"] = _gather(lp["attn_out_w"], "fsdp", 1)    # [D/tp,D]
    gw["mlp_up_w"] = _gather(lp["mlp_up_w"], "fsdp", 0)        # [D,F/tp]
    gw["mlp_down_w"] = _gather(lp["mlp_down_w"], "fsdp", 1)    # [F/tp,D]
    return gw


def _gpt_stage_compute(gw, x, cfg, tp: int, tp_axis: str):
    """One transformer block over this rank's tp shard (models/gpt._block
    semantics, hand-partitioned) given pre-gathered weights `gw`. The
    fused qkv weight's [3·D] column axis concatenates q|k|v, so its tp
    shard is NOT a head block — gather the columns once and slice this
    rank's heads out of each of q/k/v (exact: column selection commutes
    with the matmul)."""
    from ..models.gpt import _ln
    D = cfg.hidden_size
    H, hd = cfg.num_heads, cfg.head_dim
    h_loc, d_loc = H // tp, D // tp
    ti = jax.lax.axis_index(tp_axis)
    B, S, _ = x.shape

    h = x
    a_in = _ln(h, gw["ln1_scale"], gw["ln1_bias"], cfg.layer_norm_eps)
    w_qkv = gw["qkv_w"]                                        # [D, 3D]
    b_qkv = gw.get("qkv_b")                                    # [3D]

    def head_cols(w, j):
        return jax.lax.dynamic_slice_in_dim(w, j * D + ti * d_loc, d_loc,
                                            axis=-1)

    qkv_loc = []
    for j in range(3):
        p_j = jnp.einsum("bsd,df->bsf", a_in,
                         head_cols(w_qkv, j).astype(a_in.dtype))
        if b_qkv is not None:
            p_j = p_j + head_cols(b_qkv, j).astype(p_j.dtype)
        qkv_loc.append(p_j.reshape(B, S, h_loc, hd))
    q, k, v = qkv_loc
    from ..kernels.flash_attention import flash_attention_fn
    ctx = flash_attention_fn(q, k, v, causal=True).reshape(B, S, d_loc)
    w_o = gw["attn_out_w"]                                     # [D/tp, D]
    a = jax.lax.psum(
        jnp.einsum("bsd,df->bsf", ctx, w_o.astype(ctx.dtype)), tp_axis)
    if gw.get("attn_out_b") is not None:
        a = a + gw["attn_out_b"].astype(a.dtype)
    h = h + a

    m_in = _ln(h, gw["ln2_scale"], gw["ln2_bias"], cfg.layer_norm_eps)
    w_up = gw["mlp_up_w"]                                      # [D, F/tp]
    mh = jnp.einsum("bsd,df->bsf", m_in, w_up.astype(m_in.dtype))
    if gw.get("mlp_up_b") is not None:
        mh = mh + gw["mlp_up_b"].astype(mh.dtype)
    mh = jax.nn.gelu(mh)
    w_dn = gw["mlp_down_w"]                                    # [F/tp, D]
    mo = jax.lax.psum(
        jnp.einsum("bsf,fd->bsd", mh, w_dn.astype(mh.dtype)), tp_axis)
    if gw.get("mlp_down_b") is not None:
        mo = mo + gw["mlp_down_b"].astype(mo.dtype)
    return h + mo


def _gpt_pp_ce(params, toks, cfg, tp: int, tp_axis: str, pp: int,
               microbatches: int, overlap: bool = False):
    from ..models import gpt as gpt_mod
    inp, tgt = toks[:, :-1], toks[:, 1:]
    S = inp.shape[1]
    wte = _gather(params["wte"], "fsdp", 1)                   # [V/tp, D]
    wpe = _gather(params["wpe"], "fsdp", 1)                   # [Smax, D]
    x = _vocab_parallel_embed(wte, inp, tp_axis).astype(cfg.dtype)
    x = x + wpe[:S][None].astype(cfg.dtype)
    stacked = {k: params[k] for k in gpt_mod._BLOCK_KEYS_DENSE
               if k in params}
    gather = functools.partial(_gpt_gather_weights, tp_axis=tp_axis)
    compute = functools.partial(_gpt_stage_compute, cfg=cfg, tp=tp,
                                tp_axis=tp_axis)
    y, stats = _run_pipeline(stacked, x, gather, compute, pp,
                             microbatches, remat=cfg.remat,
                             overlap=overlap)
    y = gpt_mod._ln(y, params["ln_f_scale"], params["ln_f_bias"],
                    cfg.layer_norm_eps)
    logits = jnp.einsum("bsd,vd->bsv", y, wte.astype(y.dtype))
    return _vocab_parallel_ce(logits, tgt, tp_axis), stats


# ----------------------------------------------------- family: Llama
def _llama_gather_weights(lp):
    """Llama's per-layer ZeRO-3 gathers — the overlap seam (see
    _gpt_gather_weights). Norm scales copy through."""
    gw = dict(lp)
    for k in ("q_w", "k_w", "v_w", "gate_w", "up_w"):
        gw[k] = _gather(lp[k], "fsdp", 0)
    for k in ("o_w", "down_w"):
        gw[k] = _gather(lp[k], "fsdp", 1)
    return gw


def _llama_stage_compute(gw, x, cfg, tp: int, tp_axis: str, cos, sin):
    """models/llama._block over this rank's tp shard, given pre-gathered
    weights `gw`. The separate q/k/v leaves column-shard straight into
    contiguous head blocks (no fused-qkv reshuffle); GQA holds KV/tp
    kv-heads per rank, and the repeat factor H//KV aligns them with
    this rank's query heads."""
    from ..models.llama import _rmsnorm, _apply_rope
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    h_loc, kv_loc = H // tp, KV // tp
    B, S, D = x.shape

    h = _rmsnorm(x, gw["attn_norm"], cfg.rms_eps)
    q = (h @ gw["q_w"].astype(h.dtype)).reshape(B, S, h_loc, hd)
    k = (h @ gw["k_w"].astype(h.dtype)).reshape(B, S, kv_loc, hd)
    v = (h @ gw["v_w"].astype(h.dtype)).reshape(B, S, kv_loc, hd)
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    from ..kernels.flash_attention import flash_attention_fn
    ctx = flash_attention_fn(q, k, v, causal=True)
    w_o = gw["o_w"]                                    # [(H·hd)/tp, D]
    x = x + jax.lax.psum(
        ctx.reshape(B, S, h_loc * hd) @ w_o.astype(x.dtype), tp_axis)

    hh = _rmsnorm(x, gw["ffn_norm"], cfg.rms_eps)
    gated = jax.nn.silu(
        hh @ gw["gate_w"].astype(hh.dtype)) * (
        hh @ gw["up_w"].astype(hh.dtype))
    w_dn = gw["down_w"]                                # [F/tp, D]
    x = x + jax.lax.psum(gated @ w_dn.astype(x.dtype), tp_axis)
    return x


def _llama_pp_ce(params, toks, cfg, tp: int, tp_axis: str, pp: int,
                 microbatches: int, overlap: bool = False):
    from ..models import llama as llama_mod
    inp, tgt = toks[:, :-1], toks[:, 1:]
    S = inp.shape[1]
    wte = _gather(params["wte"], "fsdp", 1)                   # [V/tp, D]
    x = _vocab_parallel_embed(wte, inp, tp_axis).astype(cfg.dtype)
    cos, sin = llama_mod._rope_tables(S, cfg.head_dim, cfg.rope_theta)
    stacked = {k: params[k] for k in llama_mod._BLOCK_KEYS
               if k in params}
    compute = functools.partial(_llama_stage_compute, cfg=cfg, tp=tp,
                                tp_axis=tp_axis, cos=cos, sin=sin)
    y, stats = _run_pipeline(stacked, x, _llama_gather_weights, compute,
                             pp, microbatches, remat=cfg.remat,
                             overlap=overlap)
    y = llama_mod._rmsnorm(y, params["norm_f"], cfg.rms_eps)
    logits = jnp.einsum("bsd,vd->bsv", y, wte.astype(y.dtype))
    return _vocab_parallel_ce(logits, tgt, tp_axis), stats


def _family_of(cfg) -> str:
    name = type(cfg).__name__
    if "Llama" in name or hasattr(cfg, "num_kv_heads"):
        return "llama"
    if "GPT" in name or hasattr(cfg, "pipeline_microbatches"):
        return "gpt"
    raise NotImplementedError(
        f"pipeline-parallel training supports the gpt/llama stacked-"
        f"scan families; got config {name}")


# ------------------------------------------------------- the step builder
def make_pp_step_fn(cfg, plan, mesh, lr: float = 3e-4,
                    with_stats: bool = False, overlap=None, **adamw_kw):
    """Build the facade-contract pp>1 train step fn for (cfg, plan):
    `(params, opt_state, batch) -> (loss, new_params, new_opt)` — plus
    a trailing schedule-measured bubble-fraction scalar under
    `with_stats=True`. The fn traces ONE full-manual shard_map over the
    plan's mesh; models.facade.make_train_step wraps it in the pinned
    _ShardedTrainStep machinery (resolve_plan_step is the seam the
    resilient guard and the telemetry instrumenter route through).

    `overlap` (None = follow `plan.overlap`) selects _run_pipeline's
    double-buffered ZeRO-3 gather prefetch
    (docs/parallel_training.md §Collective overlap)."""
    family = _family_of(cfg)
    if overlap is None:
        overlap = bool(getattr(plan, "overlap", False))
    overlap = bool(overlap)
    pp = int(plan.axes.get("pp", 1))
    if pp <= 1:
        raise ValueError("make_pp_step_fn needs a plan with a pp>1 axis"
                         " — use the GSPMD 3D step otherwise")
    tp_axis = plan.mapping.get("mp", "tp")
    tp = int(plan.axes.get(tp_axis, 1))
    dp = int(plan.axes.get("dp", 1))
    fsdp = int(plan.axes.get("fsdp", 1))
    microbatches = int(getattr(plan.plan, "microbatches", 0) or 0)
    if microbatches < 2:
        raise ValueError(
            f"plan {plan.name} carries microbatches={microbatches}; the "
            "pipelined step needs >=2 (plan_train picks them for pp>1 "
            "plans)")
    missing = [a for a in ("dp", "fsdp", tp_axis, "pp")
               if a not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"the pp train step needs all of dp/fsdp/{tp_axis}/pp as "
            f"mesh axes (degree 1 included); mesh {dict(mesh.shape)} "
            f"lacks {missing}")
    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError(
            "MoE under pipeline parallelism is not implemented (the "
            "expert dispatch needs its own manual partitioning)")
    if getattr(cfg, "context_parallel", "none") not in ("none",):
        raise NotImplementedError(
            "context parallelism does not compose with the manual pp "
            "step yet")
    if family == "llama" and tp > 1 and cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} does not divide num_kv_heads={cfg.num_kv_heads} "
            "(the manual GQA split holds KV/tp kv-heads per rank)")
    specs: Dict = plan.specs or {}
    ce_fn = {"gpt": _gpt_pp_ce, "llama": _llama_pp_ce}[family]
    axis_names = tuple(str(a) for a in mesh.axis_names)
    n_grid = dp * fsdp * tp  # loss-replication factor (pp is masked)

    import jax.tree_util as jtu

    def _spec_for(path, leaf):
        return _clean_spec(specs.get(_leaf_name(path), P()), mesh,
                           getattr(leaf, "shape", ()))

    def _state_specs(tree):
        return jtu.tree_map_with_path(_spec_for, tree)

    def _batch_specs(tree):
        def pin(leaf):
            nd = len(getattr(leaf, "shape", ()))
            return P(("dp", "fsdp"), *([None] * (nd - 1))) if nd else P()
        return jax.tree_util.tree_map(pin, tree)

    def _reduce_grads(grads):
        """psum each leaf over exactly the axes its spec does NOT name:
        sharded axes already carry complete shard-gradients (the gather
        transposes reduce-scattered them), replicated axes hold
        per-rank partials (dp batch shards, the pp stage mask, the
        tp-replicated norm/bias paths)."""
        def red(path, g):
            named = _spec_axes(specs.get(_leaf_name(path), P()))
            over = tuple(a for a in axis_names if a not in named)
            return jax.lax.psum(g, over) if over else g
        return jtu.tree_map_with_path(red, grads)

    def local_step(params, opt_state, batch):
        toks = batch["tokens"] if isinstance(batch, dict) else batch
        if toks.shape[0] % microbatches:
            raise ValueError(
                f"per-shard batch {toks.shape[0]} is not divisible by "
                f"microbatches={microbatches} (plan {plan.name})")

        def loss_fn(p):
            ce, stats = ce_fn(p, toks, cfg, tp, tp_axis, pp,
                              microbatches, overlap)
            stage = jax.lax.axis_index("pp")
            # per-device PARTIAL loss: masked to the LAST stage (where
            # the pipeline's outputs are real — the mask also routes
            # the head/final-norm cotangents to exactly one stage) and
            # divided by the dp·fsdp·tp replication, so the per-device
            # contributions sum to the global mean exactly once —
            # under check_rep=False psum transposes to psum, and this
            # is the formulation whose adjoint is exact
            part = ce * (stage == pp - 1).astype(ce.dtype) / n_grid
            return part, stats

        (part, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        loss = jax.lax.psum(part, axis_names)
        grads = _reduce_grads(grads)
        from ..models.gpt import apply_adamw
        new_params, new_opt = apply_adamw(grads, params, opt_state, lr,
                                          **adamw_kw)
        out = (loss, new_params, new_opt)
        if with_stats:
            bubble = 1.0 - stats["busy"] / (stats["stages"]
                                            * stats["ticks"])
            out = out + (bubble,)
        return out

    def step(params, opt_state, batch):
        in_specs = (_state_specs(params), _state_specs(opt_state),
                    _batch_specs(batch))
        out_specs = (P(), in_specs[0], in_specs[1])
        if with_stats:
            out_specs = out_specs + (P(),)
        sm = shard_map(local_step, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, axis_names=set(axis_names),
                       check_vma=False)
        return sm(params, opt_state, batch)

    step.plan = plan
    step.microbatches = microbatches
    step.overlap = overlap
    return step
