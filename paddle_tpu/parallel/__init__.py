"""paddle_tpu.parallel — the distributed stack.

Reference analog: python/paddle/distributed/ (L5 in SURVEY.md). Exposed
both as paddle_tpu.parallel and paddle_tpu.distributed.
"""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized,
    ParallelEnv, device_count, local_device_count)
from .mesh import (  # noqa: F401
    build_mesh, set_global_mesh, get_mesh, use_mesh, sharding_for,
    shard_value, constraint, remap_spec_axes, remap_specs, tp_specs, P)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, CommGroup,
    set_hybrid_communicate_group, get_hybrid_communicate_group)
from .collective import (  # noqa: F401
    ReduceOp, all_reduce, all_gather, broadcast, barrier, scatter, reduce,
    reduce_scatter, all_to_all, send, recv, new_group, get_group, wait,
    psum, pmean, pmax, ppermute, axis_index)
from .data_parallel import DataParallel  # noqa: F401
from .checkpoint import (  # noqa: F401
    save_sharded, load_sharded, save_train_state, load_train_state,
    verify_checkpoint, CheckpointManager, CheckpointCorruptError,
    AsyncSaveError, HostSnapshot, Converter)
# NOTE: .resilience is NOT imported here — it imports
# distributed.launch.heartbeat, and distributed/__init__ imports this
# package; import it directly (paddle_tpu.parallel.resilience).
from .sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model, GroupShardedStage2,
    GroupShardedStage3, GroupShardedOptimizerStage2, shard_model_stage3,
    shard_optimizer_state)
from .compression import (  # noqa: F401
    compressed_psum, dgc_compress, dgc_decompress, dgc_psum,
    local_sgd_sync)
from .host_pipeline import HostPipeline  # noqa: F401
from .pipeline import (  # noqa: F401
    spmd_pipeline, pipeline_forward, PipelineLayer, LayerDesc,
    SharedLayerDesc)
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)
from .random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed)
from .moe import (  # noqa: F401
    MoELayer, moe_ffn, topk_gating, compute_capacity, GATES)
from . import fleet  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: paddle.distributed.spawn. Single-controller JAX drives all
    local chips from one process — spawn degenerates to a direct call. A
    request for nprocs>1 would otherwise "pass" while silently running
    world_size=1 (VERDICT r2 weak #6), so it warns loudly."""
    if nprocs not in (-1, 0, 1):
        import warnings
        warnings.warn(
            f"paddle_tpu.distributed.spawn(nprocs={nprocs}) runs func ONCE "
            f"in-process: JAX is single-controller (all local chips belong "
            f"to this process; parallelism comes from the mesh, not from "
            f"worker processes). For true multi-process jobs use "
            f"`python -m paddle_tpu.distributed.launch --nproc_per_node "
            f"{nprocs}`.", RuntimeWarning, stacklevel=2)
    func(*args)


def launch():
    from .launch.main import main
    main()
