"""Resilient training step loop: non-finite skip, rollback, watchdog.

Reference analog: the ElasticManager fault watch + restart protocol
(/root/reference/python/paddle/distributed/fleet/elastic/manager.py:124,
exit codes manager.py:30-31) and the AMP GradScaler's found_inf
skip-update semantics (amp/grad_scaler.py here generalizes the same
guard to ANY train step, not just scaled ones). The reference has no
step-level watchdog or automatic rollback; this module exceeds it
because our hardware path (the flapping TPU tunnel, CLAUDE.md) makes a
hung dispatch an expected fault, not an anomaly.

Three guards compose around `models.facade.make_train_step`:

- **skip-step**: the jitted step returns `(loss, params', opt', ok)`
  where `ok = isfinite(loss)`; when not ok the new params/opt trees are
  replaced IN-JIT by the old ones (`jnp.where` select, so donation stays
  legal), i.e. a non-finite step is a no-op update — the GradScaler
  found_inf pattern without a scaler.
- **rollback**: after `rollback_after` consecutive skipped steps the
  trainer reloads the newest intact snapshot from its CheckpointManager
  (checksum-verified, falls back past corrupt ones) and rewinds its step
  counter — divergence that a skip cannot absorb gets cut at the last
  good state.
- **watchdog**: host pulls of the step's results run under a wall-clock
  budget with bounded retry + exponential backoff (a tunnel flap stalls
  ANY pull for minutes; re-polling the same future is the only safe
  retry since donated buffers cannot be re-dispatched). When the budget
  is exhausted the worker exits with ELASTIC_EXIT_CODE (101, the
  reference's elastic protocol) so the launcher restarts the pod and
  the restarted process resumes from the LATEST pointer.
"""
from __future__ import annotations

import functools
import sys
import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from .checkpoint import _UNSET, CheckpointManager
from ..distributed.launch.heartbeat import ELASTIC_EXIT_CODE  # noqa: F401

# Fault-injection seam (paddle_tpu.testing.faults): called with the step
# index about to run; returns a loss multiplier (1.0, or nan to poison)
# and may side-effect (kill the process, stall the heartbeat). Production
# code never sets it.
_STEP_HOOK: Optional[Callable[[int], float]] = None


class StepHungError(RuntimeError):
    """A device->host pull outlived the watchdog budget (hung dispatch —
    on this hardware usually the TPU tunnel flapping)."""


def plan_state_specs(plan):
    """The restore-layout tree for a TrainPlan's trainer state: params
    per the plan's remapped PARAM_SPECS, Adam m/v mirroring them leaf
    for leaf (the facade pin rule). ONE home — ResilientTrainer's
    ctor/rebuild, the elastic controller's reshard-restore and the
    chaos drill all derive the layout here, so an optimizer-state
    shape change cannot drift between them. None when the plan carries
    no spec table."""
    if plan is None or not getattr(plan, "specs", None):
        return None
    return {"params": plan.specs,
            "opt_state": {"m": plan.specs, "v": plan.specs}}


@dataclass
class ResilienceConfig:
    """Knobs for ResilientTrainer (defaults are safe-but-lenient)."""
    rollback_after: int = 3        # consecutive skipped steps -> rollback
    max_rollbacks: int = 5         # give up (raise) after this many
    checkpoint_every: int = 0      # steps between snapshots (0 = manual)
    async_checkpoint: bool = False  # save via manager.save_async: the
    #                                 disk write leaves the step path
    #                                 (docs/parallel_training.md)
    watchdog_timeout: float = 0.0  # seconds per host pull (0 = no watchdog)
    retries: int = 3               # extra backoff waits after the timeout
    backoff_base: float = 2.0      # first retry wait, doubling each retry
    backoff_max: float = 60.0      # per-retry wait ceiling
    exit_on_hang: bool = False     # sys.exit(ELASTIC_EXIT_CODE) on hang


def make_resilient_step(step_fn, cfg=None, donate: bool = True,
                        telemetry=None, mesh=None, plan=None, **step_kw):
    """Build the guarded jitted step:
    `(params, opt_state, batch, poison) -> (loss, params', opt', ok)`.

    `mesh`/`plan` (parallel.planner.plan_train) pass straight through to
    models.facade.make_train_step: the guard (select + ok flag) and the
    telemetry accumulator ride the planner-driven GSPMD step unchanged —
    the select is elementwise (sharding-preserving) and the ok/loss
    scalars replicate, so the sharded pins hold leaf for leaf.

    `step_fn(params, opt_state, batch, ...) -> (loss, new_params,
    new_opt)` is the same contract `models.facade.make_train_step` takes;
    params/opt buffers are donated identically. `poison` is a loss
    multiplier (normally 1.0) that the chaos harness sets to nan —
    multiplying INSIDE the jit means injected and organic non-finite
    losses exercise the exact same guard. `ok` requires the loss AND
    every updated param/opt leaf to be finite (a backward pass can
    overflow while the loss is still finite — committing, let alone
    snapshotting, NaN params would defeat rollback); when not ok the
    returned trees are the (unchanged) inputs and the returned loss is
    nan, so ONE host pull of the loss communicates both values.

    With `telemetry` (a profiler.telemetry.TelemetryPipeline) the step
    additionally takes and returns the donated device accumulator —
    `(params, opt_state, batch, poison, tstate) -> (loss, params',
    opt', ok, tstate')` — recording the RAW (pre-select) loss, update
    global-norm, param global-norm and non-finite count in-jit, so a
    diverging run's telemetry shows the actual blow-up, not the
    nan-folded skip."""
    import jax
    import jax.numpy as jnp
    from ..models.facade import make_train_step, plan_step_cell
    # pp>1 plans swap the family step for the full-manual pipelined one
    # HERE (the guard wraps the resolved fn, so the select + ok flag
    # ride the 4D step exactly like the 3D one); the cell's
    # _plan_rebuild hook lets the elastic rebuild seam re-resolve the
    # pipelined inner against a degraded mesh (a pp closure bakes the
    # stage grid in; 3D closures are mesh-agnostic) — see
    # models/facade.plan_step_cell for the fresh-identity subtlety
    inner, _outer, _plan_rebuild = plan_step_cell(
        step_fn, cfg=cfg, mesh=mesh, plan=plan, **step_kw)

    def tree_finite(tree):
        fin = jnp.asarray(True)
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                fin &= jnp.all(jnp.isfinite(leaf))
        return fin

    def guard(params, opt_state, batch, poison):
        loss, new_params, new_opt = inner(params, opt_state, batch)
        loss = loss * poison
        ok = (jnp.isfinite(loss) & tree_finite(new_params)
              & tree_finite(new_opt))

        def keep(new, old):
            return jnp.where(ok, new, old)

        kept_params = jax.tree_util.tree_map(keep, new_params, params)
        kept_opt = jax.tree_util.tree_map(keep, new_opt, opt_state)
        return loss, new_params, kept_params, kept_opt, ok

    def guarded(params, opt_state, batch, poison):
        loss, _raw_params, kept_params, kept_opt, ok = guard(
            params, opt_state, batch, poison)
        return jnp.where(ok, loss, jnp.nan), kept_params, kept_opt, ok

    guarded._plan_resolved = True
    guarded._plan_rebuild = _plan_rebuild
    _outer["fn"] = guarded
    if telemetry is None:
        # the facade owns the jit/donation policy (ONE home — see
        # models/facade.py); the guard only adds the select + ok flag
        return make_train_step(guarded, donate=donate, mesh=mesh,
                               plan=plan)

    from ..profiler.telemetry import global_norm, nonfinite_count

    def guarded_telemetry(params, opt_state, batch, poison, tstate):
        loss, raw_params, kept_params, kept_opt, ok = guard(
            params, opt_state, batch, poison)
        scalars = {
            "loss": loss,                      # raw: shows the divergence
            "update_norm": global_norm(jax.tree_util.tree_map(
                lambda n, o: jnp.asarray(n, jnp.float32)
                - jnp.asarray(o, jnp.float32), raw_params, params)),
            "param_norm": global_norm(kept_params),
            "nonfinite": nonfinite_count(raw_params),
            "ok": ok,
        }
        tstate = telemetry.device_record(
            tstate, **{k: v for k, v in scalars.items()
                       if k in telemetry.fields})
        return (jnp.where(ok, loss, jnp.nan), kept_params, kept_opt, ok,
                tstate)

    guarded_telemetry._plan_resolved = True
    guarded_telemetry._plan_rebuild = _plan_rebuild
    _outer["fn"] = guarded_telemetry
    return make_train_step(guarded_telemetry, donate=donate,
                           extra_donate=(4,), mesh=mesh, plan=plan)


# telemetry field layout for the resilient trainer's pipeline (the
# default DEFAULT_FIELDS carries grad_norm/lr, which the guarded step
# cannot see — pass these to TelemetryPipeline(fields=...))
RESILIENT_FIELDS = ("loss", "update_norm", "param_norm", "nonfinite", "ok")


def pull_with_watchdog(value, timeout: float, retries: int = 3,
                       backoff_base: float = 2.0,
                       backoff_max: float = 60.0,
                       label: str = "step",
                       on_retry=None) -> np.ndarray:
    """Force `value` to a host array under a wall-clock budget.

    `jax.block_until_ready` can return early over the tunnel (CLAUDE.md),
    so forcing is a real `np.asarray` pull, run in a worker thread. The
    first wait is `timeout`; each of `retries` further waits doubles from
    `backoff_base` (capped at `backoff_max`) — re-polling the SAME pending
    future, because with donated input buffers a re-dispatch is illegal.
    Raises StepHungError when the budget is exhausted.

    `value` may be a zero-arg callable producing the array — the whole
    call then runs under the watchdog clock (the serving engine wraps
    its pull this way so injected stalls are monitored too). `on_retry`
    (if given) observes each backoff attempt index — the serving
    engine's retries counter hangs off it."""
    def force():
        return np.asarray(value() if callable(value) else value)

    if timeout <= 0:
        return force()
    box: dict = {}

    def work():
        try:
            box["val"] = force()
        except BaseException as e:          # surfaced to the caller
            box["err"] = e

    t = threading.Thread(target=work, name="paddle-watchdog-pull",
                         daemon=True)
    t.start()
    waited = 0.0
    for attempt in range(retries + 1):
        grace = timeout if attempt == 0 else min(
            backoff_base * (2.0 ** (attempt - 1)), backoff_max)
        t.join(grace)
        waited += grace
        if not t.is_alive():
            break
        if attempt < retries:
            print(f"[resilience] {label} pull stalled {waited:.1f}s "
                  f"(attempt {attempt + 1}/{retries + 1}); backing off",
                  file=sys.stderr, flush=True)
            if on_retry is not None:
                on_retry(attempt)
    if t.is_alive():
        raise StepHungError(
            f"{label} result did not arrive within {waited:.1f}s "
            f"(watchdog {timeout}s + {retries} backoff retries) — hung "
            f"dispatch (tunnel flap?)")
    if "err" in box:
        raise box["err"]
    return box["val"]


class WatchdogPuller:
    """Persistent-thread variant of `pull_with_watchdog` for
    high-frequency callers (the serving engine's ~2 ms decode tick:
    spawning a fresh pull thread per tick costs more than the guard
    protects). ONE daemon worker is reused across pulls; each pull is
    a queue round-trip under the same budget/backoff semantics.
    Responses are sequence-tagged so a pull that outlives its budget
    (StepHungError) cannot deliver its late result to a later call."""

    def __init__(self, label: str = "pull"):
        import queue
        self._label = label
        self._req: "queue.SimpleQueue" = queue.SimpleQueue()
        self._res: "queue.SimpleQueue" = queue.SimpleQueue()
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def _ensure(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=f"paddle-watchdog-{self._label}",
                daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while True:
            seq, value = self._req.get()
            try:
                res = value() if callable(value) else value
                # tuple results pass through element-wise (the serving
                # tick's token + telemetry pair rides ONE pull); a
                # ragged tuple must not collapse into an object array
                arr = (tuple(np.asarray(v) for v in res)
                       if isinstance(res, tuple)
                       else np.asarray(res))
                self._res.put((seq, "ok", arr))
            except BaseException as e:      # surfaced to the caller
                self._res.put((seq, "err", e))

    def pull(self, value, timeout: float, retries: int = 3,
             backoff_base: float = 2.0, backoff_max: float = 60.0,
             on_retry=None) -> np.ndarray:
        """Same contract as `pull_with_watchdog` (callable values run
        under the clock; `on_retry` observes backoffs; StepHungError
        on an exhausted budget)."""
        import queue
        if timeout <= 0:
            res = value() if callable(value) else value
            return (tuple(np.asarray(v) for v in res)
                    if isinstance(res, tuple) else np.asarray(res))
        self._ensure()
        self._seq += 1
        seq = self._seq
        self._req.put((seq, value))
        waited, attempt = 0.0, 0
        while attempt <= retries:
            grace = timeout if attempt == 0 else min(
                backoff_base * (2.0 ** (attempt - 1)), backoff_max)
            try:
                rseq, kind, payload = self._res.get(timeout=grace)
            except queue.Empty:
                waited += grace
                if attempt < retries:
                    print(f"[resilience] {self._label} pull stalled "
                          f"{waited:.1f}s (attempt {attempt + 1}/"
                          f"{retries + 1}); backing off",
                          file=sys.stderr, flush=True)
                    if on_retry is not None:
                        on_retry(attempt)
                attempt += 1
                continue
            if rseq != seq:
                continue       # late result of a previously hung pull
            if kind == "err":
                raise payload
            return payload
        # the worker is wedged in the hung pull: abandon it (fresh
        # queues + a fresh thread on the next call) so ONE dead dispatch
        # cannot queue-block every later, healthy pull behind it — the
        # old daemon thread leaks until its pull resolves, same as a
        # pull_with_watchdog thread would
        self._thread = None
        self._req = queue.SimpleQueue()
        self._res = queue.SimpleQueue()
        raise StepHungError(
            f"{self._label} result did not arrive within {waited:.1f}s "
            f"(watchdog {timeout}s + {retries} backoff retries) — hung "
            f"dispatch (tunnel flap?)")


class ResilientTrainer:
    """Owns (params, opt_state, step) and runs guarded steps with
    skip/rollback/watchdog + heartbeat + periodic snapshots.

    Typical wiring (the chaos drill's worker is the executable version):

        mgr = CheckpointManager(ckpt_root, max_to_keep=3)
        tr = ResilientTrainer(train_step, params, opt_state, cfg=cfg,
                              manager=mgr,
                              config=ResilienceConfig(checkpoint_every=1))
        tr.maybe_resume()            # restart -> continue from LATEST
        while tr.step < total:
            loss, ok = tr.train_step(batch_for(tr.step))
    """

    def __init__(self, step_fn, params, opt_state, *, cfg=None,
                 manager: Optional[CheckpointManager] = None,
                 config: Optional[ResilienceConfig] = None,
                 step: int = 0, donate: bool = True, mesh=_UNSET,
                 specs=None, telemetry=None, plan=None, **step_kw):
        self.config = config or ResilienceConfig()
        # restore layout: rollback must reload onto the SAME mesh/specs
        # the trainer resumed/trained with, not whatever mesh is ambient
        # at rollback time
        self._mesh = mesh
        self._specs = specs
        self.telemetry = telemetry
        # a real mesh + plan makes the guarded step the planner-driven
        # GSPMD one (docs/parallel_training.md); restore then reloads
        # onto that same mesh via the layout fields above. With a plan
        # and no explicit specs, rollbacks/resume re-slice per the
        # plan's remapped PARAM_SPECS so the restored trees come back
        # in the executing layout. GATED ON plan: mesh= alone keeps its
        # historical meaning (restore layout ONLY, the step a plain jit
        # honoring caller-committed shardings) — without a spec table
        # the sharded builder would pin every leaf REPLICATED, silently
        # un-sharding an fsdp-laid-out trainer.
        step_mesh = mesh if (plan is not None
                             and mesh not in (_UNSET, None)) else None
        if plan is not None and specs is None and plan.specs:
            self._specs = plan_state_specs(plan)
        self._guarded = make_resilient_step(step_fn, cfg=cfg,
                                            donate=donate,
                                            telemetry=telemetry,
                                            mesh=step_mesh, plan=plan,
                                            **step_kw)
        # created lazily at the first step so the device cursor seeds
        # from the RESUMED step (maybe_resume runs after __init__): a
        # restarted worker's records then continue the shared JSONL's id
        # space instead of re-emitting step 0.. over the pre-crash ones
        self._tstate = None
        self.params = params
        self.opt_state = opt_state
        self.step = int(step)
        self.manager = manager
        self.skipped = 0
        self.rollbacks = 0
        self._bad_streak = 0
        # liveness: no-op unless the launcher exported the contract
        from ..distributed.launch import heartbeat
        heartbeat.start_from_env()
        self._heartbeat = heartbeat
        # observability: monitor counters + the crash flight recorder
        # (dumps are no-ops until $PADDLE_TPU_FLIGHT_DIR is set — the
        # launcher exports it per worker)
        from ..profiler import monitor
        from ..profiler import flight_recorder
        self._mon_skip = monitor.counter("resilience_skip_step")
        self._mon_rollback = monitor.counter("resilience_rollback")
        self._mon_hang = monitor.counter("resilience_watchdog_hang")
        self._mon_steps = monitor.counter("resilience_steps")
        self._mon_step_ms = monitor.gauge("resilience_step_ms")
        self._flight = flight_recorder.recorder()
        self._flight.install_exit_hooks()
        c = self.config
        self._flight.configure(
            trainer="ResilientTrainer", start_step=self.step,
            rollback_after=c.rollback_after, max_rollbacks=c.max_rollbacks,
            checkpoint_every=c.checkpoint_every,
            watchdog_timeout=c.watchdog_timeout)

    # ------------------------------------------------------------- resume
    def maybe_resume(self, mesh=_UNSET, specs=None) -> bool:
        """Load the newest intact snapshot (LATEST-pointed first) if one
        exists; returns True when state was restored. An explicit
        `mesh`/`specs` here also becomes the layout rollbacks reload
        onto."""
        if self.manager is None:
            return False
        if mesh is not _UNSET:
            self._mesh = mesh
        if specs is not None:
            self._specs = specs
        state, step = self.manager.restore(mesh=self._mesh,
                                           specs=self._specs)
        if state is None:
            return False
        self.params = state["params"]
        self.opt_state = state.get("opt_state", self.opt_state)
        saved = state.get("step")
        self.step = int(saved) if saved is not None else int(step or 0)
        return True

    # ------------------------------------------------------------- replan
    def rebuild_plan(self, mesh, plan, *, params=None, opt_state=None,
                     step=None) -> None:
        """Elastic replan seam (parallel/elastic.py): re-target the
        guarded step at a degraded mesh/plan via the facade's
        `_ShardedTrainStep.rebuild` (same step object, fresh pins, one
        new executable — no cache-key bifurcation), swap the restore
        layout to the new plan's specs, and optionally install the
        reshard-restored state. The telemetry device accumulator lived
        on the OLD mesh, so it resets and re-initializes lazily at the
        next step, seeded from the (restored) step counter — exactly
        the maybe_resume continuation semantics."""
        if not hasattr(self._guarded, "rebuild"):
            raise TypeError(
                "rebuild_plan needs the planner-driven sharded step "
                "(make_resilient_step with mesh= and plan=); the plain "
                "jitted step has no mesh to re-target")
        self._guarded.rebuild(mesh=mesh, plan=plan)
        self._mesh = mesh
        if plan is not None and plan.specs:
            self._specs = plan_state_specs(plan)
        self._tstate = None
        if params is not None:
            self.params = params
        if opt_state is not None:
            self.opt_state = opt_state
        if step is not None:
            self.step = int(step)
        self._bad_streak = 0

    # --------------------------------------------------------------- save
    def save(self) -> Optional[str]:
        """Snapshot the live state. With config.async_checkpoint the
        host snapshot is taken here (the donated buffers are about to be
        consumed by the next step) and the commit happens off the step
        path — manager.wait() is the barrier; rollback/restore take it
        implicitly."""
        if self.manager is None:
            return None
        state = {"params": self.params, "opt_state": self.opt_state,
                 "step": np.int64(self.step)}
        if self.config.async_checkpoint:
            return self.manager.save_async(state, self.step)
        return self.manager.save(state, self.step)

    # --------------------------------------------------------------- step
    def train_step(self, batch) -> tuple:
        """Run one guarded step on `batch`. Returns `(loss, ok)` with
        `loss` a host float (nan on a skipped step). Raises StepHungError
        when the watchdog budget is exhausted and `exit_on_hang` is off;
        exits with ELASTIC_EXIT_CODE when it is on. After a hang the
        trainer's buffers are donated-away — a restarted process must
        resume via `maybe_resume()`."""
        import time as _time
        c = self.config
        t0 = _time.perf_counter()
        poison = 1.0
        if _STEP_HOOK is not None:
            poison = _STEP_HOOK(self.step)
        if self.telemetry is not None:
            if self._tstate is None:
                self._tstate = self.telemetry.device_init(start=self.step)
            loss, params, opt, ok, self._tstate = self._guarded(
                self.params, self.opt_state, batch, poison, self._tstate)
        else:
            loss, params, opt, ok = self._guarded(
                self.params, self.opt_state, batch, poison)
        del ok                 # the guarded step folds every badness
        #                        (non-finite loss OR params OR opt) into a
        #                        nan loss, so ok derives from the one loss
        #                        pull — a second device->host pull would
        #                        cost another ~70-170 ms tunnel round trip
        #                        per step AND could hang if the tunnel
        #                        flaps between pulls
        try:
            loss_host = float(pull_with_watchdog(
                loss, c.watchdog_timeout, c.retries, c.backoff_base,
                c.backoff_max, label=f"step {self.step}"))
        except StepHungError as e:
            self._mon_hang.add()
            self._flight.configure(last_error=str(e))
            if c.exit_on_hang:
                self._flight.dump("watchdog_elastic_exit")
                print(f"[resilience] {e}; exiting "
                      f"{ELASTIC_EXIT_CODE} for elastic restart",
                      file=sys.stderr, flush=True)
                sys.exit(ELASTIC_EXIT_CODE)
            self._flight.dump("watchdog_hang")
            raise
        ok_host = bool(np.isfinite(loss_host))
        self.params, self.opt_state = params, opt
        self._heartbeat.pulse()
        self.step += 1
        dur_s = _time.perf_counter() - t0
        self._mon_steps.add()
        self._mon_step_ms.set(dur_s * 1e3)
        self._flight.note(step=self.step - 1, loss=loss_host, ok=ok_host,
                          dur_s=round(dur_s, 6))
        if self.telemetry is not None:
            self._tstate = self.telemetry.tick(self.step - 1, self._tstate)
        if ok_host:
            self._bad_streak = 0
            if (self.manager is not None and c.checkpoint_every > 0
                    and self.step % c.checkpoint_every == 0):
                self.save()
        else:
            self.skipped += 1
            self._bad_streak += 1
            self._mon_skip.add()
            print(f"[resilience] non-finite loss at step "
                  f"{self.step - 1}: update skipped "
                  f"({self._bad_streak}/{c.rollback_after} before "
                  f"rollback)", file=sys.stderr, flush=True)
            if self._bad_streak >= c.rollback_after:
                self._rollback()
        return loss_host, ok_host

    def _rollback(self) -> None:
        self._mon_rollback.add()
        # the black box captures the bad streak BEFORE the state rewinds
        self._flight.dump("rollback")
        if self.manager is None:
            # nothing to roll back to: reset the streak so training can
            # limp on with skips alone
            self._bad_streak = 0
            return
        if self.rollbacks >= self.config.max_rollbacks:
            raise RuntimeError(
                f"resilience: {self.rollbacks} rollbacks exhausted and "
                f"the loss is still non-finite — giving up")
        state, step = self.manager.restore(mesh=self._mesh,
                                           specs=self._specs)
        if state is None:
            # non-finite before the FIRST snapshot (bad init/LR, or a
            # fault injected at step 0): dying here would turn a
            # recoverable run into a crash that burns the launcher's
            # restart budget — limp on with skips like the manager-less
            # path and let max_rollbacks bound organic divergence later
            print("[resilience] rollback requested but no snapshot "
                  "exists yet; continuing with skip-only recovery",
                  file=sys.stderr, flush=True)
            self._bad_streak = 0
            return
        self.params = state["params"]
        self.opt_state = state.get("opt_state", self.opt_state)
        saved = state.get("step")
        self.step = int(saved) if saved is not None else int(step or 0)
        self.rollbacks += 1
        self._bad_streak = 0
        print(f"[resilience] rolled back to step {self.step} "
              f"(rollback {self.rollbacks}/{self.config.max_rollbacks})",
              file=sys.stderr, flush=True)


def run_resilient(trainer: ResilientTrainer, batch_fn, total_steps: int,
                  on_step: Optional[Callable[[int, float, bool], Any]]
                  = None):
    """Drive `trainer` to `total_steps`, fetching `batch_fn(step)` per
    step (deterministic batches keyed by step index make post-rollback
    re-runs bit-identical — the chaos drill relies on this). `on_step`
    observes `(step_just_run, loss, ok)`."""
    while trainer.step < total_steps:
        step = trainer.step
        loss, ok = trainer.train_step(batch_fn(step))
        if on_step is not None:
            on_step(step, loss, ok)
    return trainer
