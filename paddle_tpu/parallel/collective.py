"""Functional collectives.

Reference analog: python/paddle/distributed/communication/ (all_reduce,
all_gather, ... over ProcessGroupNCCL, process_group.h:53-430).

TPU-native, two modes:
1. *In-trace* (inside shard_map manual regions): thin wrappers over
   lax.psum/all_gather/ppermute/all_to_all — XLA lowers to ICI collectives.
2. *Eager on global arrays*: a "collective" reorganizes a global jax.Array
   across a mesh axis; implemented as a jitted shard_map computation over
   the group's axis. With no mesh (single chip) they are identities on the
   global value, matching the reference's world_size==1 fast path.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.dispatch import apply
from ..framework.tensor import Tensor
from .mesh import get_mesh
from .topology import CommGroup


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis_of(group) -> Optional[str]:
    if group is None:
        mesh = get_mesh()
        if mesh is None:
            return None
        # default group = all axes
        return tuple(mesh.axis_names)
    if isinstance(group, CommGroup):
        return group.axis_name
    return group


def _in_manual_region():
    """True when called inside shard_map (axis names bound)."""
    try:
        import jax.core as jcore
        frame = jcore.get_axis_env() if hasattr(jcore, "get_axis_env") else None
    except Exception:
        frame = None
    return False


def _psum_like(x, axis, op):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


# ---------------------------------------------------------------- in-trace
def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


# ------------------------------------------------------ eager global-array
def _eager_collective(name, tensor, axis, fn_manual, out_identity=True):
    """Run a shard_map collective over `axis` on a global tensor."""
    mesh = get_mesh()
    if mesh is None or axis is None or (
            isinstance(axis, str) and axis not in mesh.axis_names):
        return tensor if out_identity else None
    from jax.sharding import NamedSharding
    from jax.experimental.shard_map import shard_map

    def _op(v, _axis=axis):
        return fn_manual(v, _axis)

    axes = axis if isinstance(axis, tuple) else (axis,)
    rest = tuple(a for a in mesh.axis_names if a not in axes)

    def _fn(v, axis=None):
        sm = shard_map(_op, mesh=mesh,
                       in_specs=P(axes),
                       out_specs=P(axes),
                       check_rep=False)
        return sm(v)
    # note: this simple spec assumes the tensor's leading dim is sharded on
    # `axes`; replicated tensors reduce to identity (handled by callers)
    return apply(name, _fn, tensor, axis=axes)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """On a replicated global array this is an identity (the sum over the
    group already happened when the global value was formed — reference's
    world_size==1 path); on a sharded array use all_gather+reduce
    explicitly. Kept for API parity; inside shard_map use psum."""
    axis = _axis_of(group)
    if axis is None:
        return tensor
    mesh = get_mesh()
    val = tensor._value
    sharding = getattr(val, "sharding", None)
    if sharding is None or not _is_sharded_on(sharding, axis):
        return tensor

    from jax.experimental.shard_map import shard_map
    axes = axis if isinstance(axis, tuple) else (axis,)

    def _fn(v, axes=None, opname=None):
        sm = shard_map(lambda s: _psum_like(s, axes, opname), mesh=mesh,
                       in_specs=P(axes), out_specs=P(axes), check_rep=False)
        return sm(v)
    out = apply("all_reduce", _fn, tensor, axes=axes, opname=op)
    tensor._value = out._value
    return tensor


def _is_sharded_on(sharding, axis):
    try:
        spec = sharding.spec
    except Exception:
        return False
    axes = axis if isinstance(axis, tuple) else (axis,)
    flat = []
    for e in spec:
        if isinstance(e, (tuple, list)):
            flat.extend(e)
        elif e is not None:
            flat.append(e)
    return any(a in flat for a in axes)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """Gather per-shard values along the group axis into a list (reference
    semantics). On a global array: slice the gathered global value."""
    axis = _axis_of(group)
    mesh = get_mesh()
    if axis is None or mesh is None:
        tensor_list.append(tensor)
        return tensor_list
    n = (group.nranks if isinstance(group, CommGroup)
         else int(np.prod([mesh.shape[a] for a in (
             axis if isinstance(axis, tuple) else (axis,))])))
    from ..ops.manipulation import split
    # gathered global view == the tensor itself; expose per-rank slices
    if tensor.shape[0] % n == 0 and n > 1:
        tensor_list.extend(split(tensor, n, axis=0))
    else:
        tensor_list.extend([tensor] * n)
    return tensor_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Global arrays are single-program values — broadcast is identity
    (reference: ProcessGroup broadcast keeps rank-src value)."""
    return tensor


def barrier(group=None):
    jax.block_until_ready(jnp.zeros(()))


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor._value = tensor_list[0]._value
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    from ..ops.math import add
    from ..ops.manipulation import concat
    total = tensor_list[0]
    for t in tensor_list[1:]:
        total = add(total, t)
    tensor._value = total._value
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Single-program view: transpose of the list structure (the MoE
    global_scatter path uses lax.all_to_all inside shard_map instead —
    see parallel.moe)."""
    out_tensor_list.extend(in_tensor_list)
    return out_tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv: use the pipeline schedule "
        "(paddle_tpu.parallel.pipeline) — on TPU p2p is a ppermute inside "
        "the compiled program, not a host-driven NCCL call")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv: use the pipeline schedule "
        "(paddle_tpu.parallel.pipeline)")


def new_group(ranks=None, backend=None, timeout=None):
    mesh = get_mesh()
    n = len(ranks) if ranks else (jax.device_count())
    return CommGroup(None, mesh, rank=0, nranks=n)


def get_group(gid=0):
    mesh = get_mesh()
    return CommGroup(None, mesh, rank=0,
                     nranks=jax.device_count())


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(tensor._value)
    return tensor
